"""Seeded, clock-injected fault scheduling for deterministic chaos tests.

The fake server (cloud/fake_server.py) already has manual fault switches
(api_down, fail_next_create, preempt(), vanish()); what those can't do is
COMPOSE into the messy overlapping reality of a real cloud week: an error
burst during a preemption storm, a latency spike right as the API heals.
``FaultPlan`` closes that gap: a seeded RNG lays out fault windows over a
time horizon, an injected clock decides which are active, and the fake
server consults the plan on every request — so a chaos soak is fully
deterministic (same seed + same request sequence = same faults) and runs
with NO real sleeps (latency is modeled by advancing the injected clock).

Every random draw comes from the plan's own ``random.Random(seed)``; the
seed is embedded in ``describe()`` so a failing soak prints its replay key.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional

# window kinds, in rough escalation order
ERROR_BURST = "error_burst"        # fraction of requests 500/503
LATENCY_SPIKE = "latency_spike"    # every request takes `param` extra seconds
BLACKOUT = "blackout"              # every request 503 (+ Retry-After)
PREEMPTION_STORM = "preemption_storm"  # ACTIVE slices get preempted
FLAKY_HEAL = "flaky_heal"          # error rate decays linearly to 0 over the window
HOST_LOSS = "host_loss"            # ONE worker of a multi-host slice dies for
                                   # the window; capacity returns when it ends

KINDS = (ERROR_BURST, LATENCY_SPIKE, BLACKOUT, PREEMPTION_STORM, FLAKY_HEAL)
# host_loss is opt-in (explicit windows): random plans keep the legacy mix so
# existing seeded soaks replay identically; elastic soaks schedule it by hand
ALL_KINDS = KINDS + (HOST_LOSS,)


@dataclasses.dataclass
class FaultWindow:
    """One scheduled fault. ``start``/``end`` are offsets (seconds) from the
    plan's birth; ``param`` is kind-specific: error probability for
    ERROR_BURST/FLAKY_HEAL, added seconds for LATENCY_SPIKE, per-slice
    preemption probability per poll for PREEMPTION_STORM, Retry-After
    seconds for BLACKOUT."""

    kind: str
    start: float
    end: float
    param: float = 0.0

    def active_at(self, t: float) -> bool:
        return self.start <= t < self.end


class FaultPlan:
    """Deterministic chaos schedule the fake server executes.

    ``clock`` is the shared injected clock (the same one the provider,
    transport and fake server use). ``advance`` (optional) is how latency
    spikes "happen": instead of sleeping, the plan advances the shared
    clock by the spike amount — wall time is untouched, simulated time
    pays the cost, and the transport's deadline budget sees it."""

    def __init__(self, seed: int, clock: Callable[[], float], *,
                 horizon_s: float = 600.0,
                 windows: Optional[list[FaultWindow]] = None,
                 advance: Optional[Callable[[float], None]] = None):
        self.seed = seed
        self.clock = clock
        self.advance = advance
        self.rng = random.Random(seed)
        self.horizon_s = horizon_s
        self.t0 = clock()
        self.windows = list(windows) if windows is not None \
            else self._generate(horizon_s)
        # what actually fired, for post-mortems
        self.injected_errors = 0
        self.injected_latency_s = 0.0
        self.preempted: list[tuple[float, str]] = []
        self.host_losses: list[tuple[float, str, int]] = []
        # host_loss bookkeeping: window index -> (slice, worker) chosen when
        # the window opened; moved to _restored once the close fired
        self._host_loss_live: dict[int, tuple[str, int]] = {}
        self._host_loss_done: set[int] = set()

    # -- schedule generation ---------------------------------------------------

    def _generate(self, horizon_s: float) -> list[FaultWindow]:
        """Random walk over the horizon: quiet gap, then a fault window, and
        again — ending with a mandatory quiet tail (>= 25% of the horizon)
        so every plan gives the system room to converge."""
        windows: list[FaultWindow] = []
        t = self.rng.uniform(5.0, horizon_s * 0.1)
        quiet_tail = horizon_s * 0.75
        while t < quiet_tail:
            kind = self.rng.choice(KINDS)
            dur = self.rng.uniform(10.0, horizon_s * 0.15)
            dur = min(dur, quiet_tail - t)
            if dur <= 0:
                break
            if kind in (ERROR_BURST, FLAKY_HEAL):
                param = self.rng.uniform(0.2, 0.8)
            elif kind == LATENCY_SPIKE:
                param = self.rng.uniform(0.5, 5.0)
            elif kind == BLACKOUT:
                param = self.rng.uniform(1.0, 10.0)  # Retry-After seconds
            else:  # PREEMPTION_STORM
                param = self.rng.uniform(0.1, 0.5)
            windows.append(FaultWindow(kind, t, t + dur, param))
            t += dur + self.rng.uniform(5.0, horizon_s * 0.1)
        return windows

    # -- queries (called by the fake server per request) -----------------------

    def _now(self) -> float:
        return self.clock() - self.t0

    def active(self, kind: Optional[str] = None) -> list[FaultWindow]:
        t = self._now()
        return [w for w in self.windows if w.active_at(t)
                and (kind is None or w.kind == kind)]

    @property
    def quiet(self) -> bool:
        """Past every window — the plan is done injecting faults."""
        return self._now() >= max((w.end for w in self.windows), default=0.0)

    def apply_latency(self):
        """Advance the injected clock by the active latency spike (if any).
        Called once per request BEFORE it is served."""
        for w in self.active(LATENCY_SPIKE):
            if self.advance is not None:
                self.advance(w.param)
            self.injected_latency_s += w.param

    def request_fault(self) -> Optional[tuple[int, dict, dict]]:
        """Should this request fail? Returns (status, body, headers) or None.
        Blackouts reject everything with a Retry-After; error bursts reject a
        seeded fraction; flaky-heal windows reject a fraction that decays
        linearly to zero across the window (the API getting better)."""
        t = self._now()
        for w in self.windows:
            if not w.active_at(t):
                continue
            if w.kind == BLACKOUT:
                self.injected_errors += 1
                return 503, {"error": "injected blackout"}, \
                    {"Retry-After": str(int(w.param))}
            if w.kind == ERROR_BURST and self.rng.random() < w.param:
                self.injected_errors += 1
                status = 503 if self.rng.random() < 0.7 else 500
                return status, {"error": "injected error burst"}, {}
            if w.kind == FLAKY_HEAL:
                frac = 1.0 - (t - w.start) / max(1e-9, w.end - w.start)
                if self.rng.random() < w.param * frac:
                    self.injected_errors += 1
                    return 503, {"error": "injected flake (healing)"}, {}
        return None

    def host_loss_transitions(self, candidates: list[tuple[str, int]]
                              ) -> list[tuple[str, int, bool]]:
        """Open/close host_loss windows against the current world.
        ``candidates``: (slice name, worker count) of ACTIVE multi-host
        slices. Returns (slice, worker_id, lost) transitions the caller must
        apply: lost=True when a window opens (kill exactly ONE worker of one
        slice — the partial-gang failure preemption storms can't model),
        lost=False when it closes (the cloud restores capacity). Victim
        choice is seeded: same seed + same request sequence = same victim.
        ``param`` >= 1 pins the worker id (int(param) % workers) for fully
        scripted soaks; param < 1 draws it from the plan's RNG."""
        t = self._now()
        out: list[tuple[str, int, bool]] = []
        for idx, w in enumerate(self.windows):
            if w.kind != HOST_LOSS:
                continue
            if w.active_at(t) and idx not in self._host_loss_live \
                    and idx not in self._host_loss_done:
                multi = sorted((n, c) for n, c in candidates if c > 1)
                if not multi:
                    continue  # nothing to lose a host from yet; retry next call
                name, count = multi[self.rng.randrange(len(multi))]
                wid = (int(w.param) % count if w.param >= 1.0
                       else self.rng.randrange(count))
                self._host_loss_live[idx] = (name, wid)
                self.host_losses.append((t, name, wid))
                out.append((name, wid, True))
            elif t >= w.end and idx in self._host_loss_live:
                name, wid = self._host_loss_live.pop(idx)
                self._host_loss_done.add(idx)
                out.append((name, wid, False))
        return out

    def preempt_victims(self, active_slices: list[str]) -> list[str]:
        """During a preemption storm, pick victims among the ACTIVE slice
        names (each independently with the window's probability). The fake
        server calls this once per request and preempts the returned ones."""
        storms = self.active(PREEMPTION_STORM)
        if not storms:
            return []
        p = max(w.param for w in storms)
        victims = [n for n in sorted(active_slices) if self.rng.random() < p]
        for v in victims:
            self.preempted.append((self._now(), v))
        return victims

    # -- replay/debug ----------------------------------------------------------

    def describe(self) -> str:
        lines = [f"FaultPlan(seed={self.seed}, horizon={self.horizon_s:.0f}s, "
                 f"errors={self.injected_errors}, "
                 f"latency={self.injected_latency_s:.1f}s, "
                 f"preemptions={len(self.preempted)}, "
                 f"host_losses={len(self.host_losses)})"]
        for w in self.windows:
            lines.append(f"  [{w.start:7.1f}s - {w.end:7.1f}s] "
                         f"{w.kind} param={w.param:.2f}")
        return "\n".join(lines)
