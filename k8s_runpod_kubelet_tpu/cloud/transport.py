"""HTTP transport with auth, timeouts, bounded retry, and a circuit breaker.

Analog of the reference's REST plumbing (runpod_client.go:742-770 makeRESTRequest:
Bearer auth, 30s default / 60s deploy timeouts) — but where the reference retried
with a linear no-jitter sleep (:275-307), this transport is hardened for the
chaos that is the COMMON case on cloud APIs (ISSUE 3):

- capped exponential backoff with decorrelated jitter (an API brownout must not
  see every kubelet retry in lockstep);
- a per-request total deadline budget that spans retries — a 30s call can never
  become 90s of hidden sleeps;
- ``Retry-After`` honored on 429/503 (seconds and HTTP-date forms);
- a closed/open/half-open circuit breaker so a dead API fails fast instead of
  soaking every control loop in timeout waits, with metrics + trace spans for
  the retry path.

stdlib-only so the control plane has zero third-party deps.
"""

from __future__ import annotations

import email.utils
import json
import logging
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Optional

log = logging.getLogger(__name__)

DEFAULT_TIMEOUT_S = 30.0
DEPLOY_TIMEOUT_S = 60.0
MAX_RETRIES = 3
BACKOFF_BASE_S = 0.5   # first-retry floor; jitter decorrelates from here
BACKOFF_CAP_S = 15.0   # no single hidden sleep longer than this
RETRY_AFTER_CAP_S = 60.0  # a hostile/buggy Retry-After can't park us for hours

# circuit-breaker state encoding (also the tpu_cloud_circuit_state gauge value)
CLOSED, OPEN, HALF_OPEN = 0, 1, 2
_STATE_NAMES = {CLOSED: "closed", OPEN: "open", HALF_OPEN: "half-open"}


class TransportError(Exception):
    """A request failed after retries. ``status`` is the last HTTP status (0 = network)."""

    def __init__(self, message: str, status: int = 0, body: str = ""):
        super().__init__(message)
        self.status = status
        self.body = body


class CircuitOpenError(TransportError):
    """Fail-fast rejection: the breaker is open (or half-open with a probe
    already in flight). No network I/O happened."""


def parse_retry_after(value: Optional[str],
                      now: Optional[float] = None) -> Optional[float]:
    """``Retry-After`` header -> seconds to wait, or None if absent/garbage.

    Handles both RFC 7231 forms: delta-seconds (``Retry-After: 7``) and
    HTTP-date (``Retry-After: Fri, 31 Dec 1999 23:59:59 GMT``). ``now`` is
    wall-clock seconds for the date math (defaults to time.time()); a date
    in the past yields 0.0 (retry immediately), not a negative sleep."""
    if not value:
        return None
    value = value.strip()
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    try:
        dt = email.utils.parsedate_to_datetime(value)
    except (TypeError, ValueError):
        return None
    if dt is None:
        return None
    if dt.tzinfo is None:
        import datetime
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    now = time.time() if now is None else now
    return max(0.0, dt.timestamp() - now)


class CircuitBreaker:
    """Closed/open/half-open breaker over consecutive transport failures.

    - CLOSED: traffic flows; ``failure_threshold`` CONSECUTIVE failures trip
      it OPEN (any success resets the streak).
    - OPEN: every ``allow()`` is rejected (callers fail fast with
      CircuitOpenError — no timeout soak) until ``reset_timeout_s`` elapses.
    - HALF_OPEN: exactly ONE probe request is allowed through; its success
      closes the breaker, its failure re-opens it for another full timeout.

    ``clock`` is injectable (monotonic by default) so chaos tests drive the
    state machine with a FakeClock. ``on_state_change(old, new)`` fires
    OUTSIDE the internal lock — the provider uses it to flip the node's
    ``TpuApiReachable`` condition + taint the moment the API goes dark."""

    def __init__(self, failure_threshold: int = 5, reset_timeout_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 metrics=None, name: str = "tpu_cloud"):
        self.failure_threshold = max(1, failure_threshold)
        self.reset_timeout_s = reset_timeout_s
        self.clock = clock
        self.metrics = metrics
        self.name = name
        self.on_state_change: Optional[Callable[[int, int], None]] = None
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        if metrics is not None:
            metrics.describe("tpu_cloud_circuit_state",
                            "circuit breaker over the cloud API: 0=closed "
                            "1=open 2=half-open")
            metrics.describe("tpu_cloud_breaker_trips",
                            "times the breaker opened (API declared dark)")
            metrics.set_gauge("tpu_cloud_circuit_state", float(CLOSED))

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    @property
    def state_name(self) -> str:
        return _STATE_NAMES[self.state]

    def _transition(self, new: int) -> Optional[tuple[int, int]]:
        """Must hold self._lock. Returns (old, new) when the state changed."""
        old = self._state
        if old == new:
            return None
        self._state = new
        return (old, new)

    def _after(self, change: Optional[tuple[int, int]]):
        """Fire metrics + callback outside the lock."""
        if change is None:
            return
        old, new = change
        log.warning("cloud circuit breaker: %s -> %s",
                    _STATE_NAMES[old], _STATE_NAMES[new])
        if self.metrics is not None:
            self.metrics.set_gauge("tpu_cloud_circuit_state", float(new))
            if new == OPEN:
                self.metrics.incr("tpu_cloud_breaker_trips")
        cb = self.on_state_change
        if cb is not None:
            try:
                cb(old, new)
            except Exception as e:  # noqa: BLE001 — observers must not break I/O
                log.warning("breaker state-change callback failed: %s", e)

    def allow(self) -> bool:
        """May a request proceed right now? OPEN->HALF_OPEN transition happens
        here (lazily, on the first call after the reset timeout)."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self.clock() - self._opened_at < self.reset_timeout_s:
                    return False
                change = self._transition(HALF_OPEN)
                self._probe_in_flight = True
            else:  # HALF_OPEN: one probe at a time
                if self._probe_in_flight:
                    return False
                self._probe_in_flight = True
                change = None
        self._after(change)
        return True

    def record_success(self):
        with self._lock:
            self._failures = 0
            self._probe_in_flight = False
            change = self._transition(CLOSED)
        self._after(change)

    def release_probe(self):
        """Release a claimed half-open probe slot WITHOUT recording an
        outcome — for a request that aborted before any I/O happened
        (degenerate deadline budget). The breaker stays half-open and the
        next allow() may start a fresh probe."""
        with self._lock:
            self._probe_in_flight = False

    def record_failure(self):
        with self._lock:
            self._failures += 1
            change = None
            if self._state == HALF_OPEN:
                # the probe failed: straight back to OPEN, fresh timeout
                self._probe_in_flight = False
                self._opened_at = self.clock()
                change = self._transition(OPEN)
            elif self._state == CLOSED and self._failures >= self.failure_threshold:
                self._opened_at = self.clock()
                change = self._transition(OPEN)
        self._after(change)


class HttpTransport:
    """Tiny JSON-over-HTTP client: request(), with bearer auth and hardened
    retry on 5xx/network.

    4xx responses are NOT retried (they are deterministic), with two carve-outs:
    - 401 when a refreshable ``token_provider`` is set: GCP access tokens
      expire hourly (unlike the reference's immortal API key,
      runpod_client.go:144), so one 401 triggers provider.invalidate() and a
      single re-issue with a fresh token before giving up.
    - 429 WITH a ``Retry-After`` header: the server explicitly asked us to
      come back, so we do — within the deadline budget. A bare 429 still
      raises immediately (the quota-error path deploy requeues on).

    ``token_provider`` is any callable returning the current bearer token
    (see cloud/gcp_auth.py); an optional ``invalidate()`` attribute enables
    the 401 refresh path. A plain ``token`` string still works and wins if
    both are given (explicit beats ambient).

    ``deadline_s`` is the TOTAL per-request budget spanning every attempt and
    every backoff sleep (default: 2x the attempt timeout). ``clock`` must be
    monotonic-ish and is injectable (chaos tests share one FakeClock across
    transport, breaker, fake server and provider). ``rng`` seeds the
    decorrelated jitter. ``breaker`` (optional) gates every request;
    ``metrics``/``tracer`` make the retry path observable."""

    def __init__(
        self,
        base_url: str,
        token: str = "",
        token_provider: Optional[Callable[[], str]] = None,
        timeout_s: float = DEFAULT_TIMEOUT_S,
        max_retries: int = MAX_RETRIES,
        sleep: Callable[[float], None] = time.sleep,
        user_agent: str = "tpu-virtual-kubelet/0.1",
        clock: Callable[[], float] = time.monotonic,
        wall_clock: Callable[[], float] = time.time,
        rng: Optional[random.Random] = None,
        deadline_s: Optional[float] = None,
        backoff_base_s: float = BACKOFF_BASE_S,
        backoff_cap_s: float = BACKOFF_CAP_S,
        breaker: Optional[CircuitBreaker] = None,
        metrics=None,
        tracer=None,
    ):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.token_provider = token_provider
        self.timeout_s = timeout_s
        self.max_retries = max_retries
        self._sleep = sleep
        self.user_agent = user_agent
        self.clock = clock
        # wall time ONLY for HTTP-date Retry-After math (clock is monotonic
        # and useless against an absolute date); injectable like clock
        self.wall_clock = wall_clock
        self.rng = rng or random.Random()
        self.deadline_s = deadline_s
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.breaker = breaker
        self.metrics = metrics
        self.tracer = tracer
        if metrics is not None:
            metrics.describe("tpu_cloud_request_retries",
                            "cloud API attempts retried after 5xx/network "
                            "failures (labels: reason)")

    def _bearer(self) -> str:
        if self.token:
            return self.token
        if self.token_provider is not None:
            return self.token_provider()
        return ""

    def _next_backoff(self, prev: float) -> float:
        """Decorrelated jitter (the AWS architecture-blog scheme): sleep is
        uniform in [base, prev*3], capped — successive retries spread out
        without synchronizing across kubelets."""
        return min(self.backoff_cap_s,
                   self.rng.uniform(self.backoff_base_s, max(self.backoff_base_s,
                                                             prev * 3.0)))

    def _note_retry(self, method: str, path: str, attempt: int,
                    started: float, err: TransportError, reason: str):
        if self.metrics is not None:
            self.metrics.incr("tpu_cloud_request_retries",
                              labels={"reason": reason})
        if self.tracer is not None:
            # one span per FAILED attempt: the retry ladder becomes visible
            # in /debug/traces without tracing every healthy call
            self.tracer.record("cloud.retry", started, self.clock(),
                               attrs={"method": method, "path": path,
                                      "attempt": attempt, "status": err.status,
                                      "reason": reason, "error": str(err)})
        log.debug("retrying %s %s (attempt %d failed): %s",
                  method, path, attempt, err)

    def request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        timeout_s: Optional[float] = None,
        expect_status: tuple[int, ...] = (200,),
        max_retries: Optional[int] = None,
        deadline_s: Optional[float] = None,
        extra_headers: Optional[dict] = None,
    ) -> Any:
        """Issue a JSON request; returns the decoded JSON body (or None for empty).

        ``max_retries`` overrides the transport-wide attempt count for calls
        whose caller would rather fail fast than block (e.g. the quota read
        that rides the readiness probe's ping path). ``deadline_s`` overrides
        the total budget for this one request. ``extra_headers`` adds
        caller headers (the fleet router propagates ``traceparent`` so a
        routed request's engine spans join the router's trace)."""
        url = self.base_url + path
        data = json.dumps(body).encode() if body is not None else None
        retries = self.max_retries if max_retries is None else max_retries
        attempt_timeout = timeout_s or self.timeout_s
        budget = deadline_s if deadline_s is not None else \
            (self.deadline_s if self.deadline_s is not None
             else attempt_timeout * 2.0)
        start = self.clock()
        deadline = start + budget
        if self.breaker is not None and not self.breaker.allow():
            raise CircuitOpenError(
                f"{method} {path}: circuit breaker is "
                f"{self.breaker.state_name} — failing fast", status=0)
        last_err: Optional[TransportError] = None
        auth_retried = False
        backoff = self.backoff_base_s
        attempt = 0
        while attempt < retries:
            attempt += 1
            attempt_started = self.clock()
            # never hand urlopen more time than the budget has left
            remaining = deadline - attempt_started
            if remaining <= 0:
                break
            this_timeout = min(attempt_timeout, remaining)
            retry_after: Optional[float] = None
            reason = ""
            req = urllib.request.Request(url, data=data, method=method)
            req.add_header("Content-Type", "application/json")
            req.add_header("User-Agent", self.user_agent)
            for hk, hv in (extra_headers or {}).items():
                req.add_header(hk, hv)
            try:
                bearer = self._bearer()
            except Exception as e:
                # transient token-fetch failure (metadata-server blip):
                # rides the same retry/backoff and keeps the TransportError
                # contract every caller catches. Counts as a breaker failure
                # too — no token means no reachable API, and (crucially) a
                # HALF_OPEN probe that dies here must release its probe slot
                # or the breaker wedges half-open forever
                last_err = TransportError(
                    f"{method} {path}: token fetch failed: {e}", status=0)
                reason = "token"
                if self.breaker is not None:
                    self.breaker.record_failure()
            else:
                if bearer:
                    req.add_header("Authorization", f"Bearer {bearer}")
                try:
                    with urllib.request.urlopen(req, timeout=this_timeout) as resp:
                        raw = resp.read()
                        if resp.status not in expect_status:
                            raise TransportError(
                                f"{method} {path}: unexpected status {resp.status}",
                                status=resp.status,
                                body=raw.decode(errors="replace"))
                        if self.breaker is not None:
                            self.breaker.record_success()
                        return json.loads(raw) if raw else None
                except urllib.error.HTTPError as e:
                    body_text = e.read().decode(errors="replace")
                    if e.code in expect_status:
                        if self.breaker is not None:
                            self.breaker.record_success()
                        return json.loads(body_text) if body_text else None
                    last_err = TransportError(
                        f"{method} {path}: HTTP {e.code}", status=e.code,
                        body=body_text)
                    retry_after = parse_retry_after(
                        e.headers.get("Retry-After") if e.headers else None,
                        now=self.wall_clock())
                    if e.code == 401 and not auth_retried and \
                            hasattr(self.token_provider, "invalidate") and \
                            not self.token:
                        # expired/revoked token: refresh once, re-issue now
                        # (does not consume a backoff-retry slot)
                        auth_retried = True
                        attempt -= 1
                        self.token_provider.invalidate()
                        log.info("401 on %s %s — refreshing bearer token",
                                 method, path)
                        continue
                    if e.code < 500:
                        # any response proves the API is alive — a 4xx must
                        # not push the breaker toward open
                        if self.breaker is not None:
                            self.breaker.record_success()
                        if e.code == 429 and retry_after is not None:
                            # throttled WITH guidance: obey it (within budget)
                            reason = "retry-after"
                        else:
                            raise last_err  # deterministic failure
                    else:
                        reason = "5xx"
                        if self.breaker is not None:
                            self.breaker.record_failure()
                except (urllib.error.URLError, TimeoutError, ConnectionError,
                        OSError) as e:
                    last_err = TransportError(f"{method} {path}: {e}", status=0)
                    reason = "network"
                    if self.breaker is not None:
                        self.breaker.record_failure()
            if attempt >= retries:
                break
            if self.breaker is not None and self.breaker.state != CLOSED:
                # the breaker (re-)opened on an attempt of THIS request —
                # e.g. a half-open probe whose first attempt failed, or a
                # failure streak crossing the threshold mid-request.
                # Retrying would backoff-sleep and do real I/O against an
                # API just declared dark; stop now with the real error
                # instead of soaking the remaining deadline budget.
                # (A pure state READ, deliberately not allow(): allow() can
                # claim the half-open probe slot, and an exit path that
                # then breaks on the deadline would leak it — wedging the
                # breaker half-open forever.)
                assert last_err is not None
                raise last_err
            sleep_s = self._next_backoff(backoff)
            backoff = sleep_s
            if retry_after is not None:
                # the server's ask wins over our jitter (capped: a hostile
                # header can't park the control loop for an hour)
                sleep_s = min(max(sleep_s, retry_after), RETRY_AFTER_CAP_S)
            if self.clock() + sleep_s >= deadline:
                # budget exhausted mid-backoff: surface the LAST REAL error,
                # annotated — a deadline is a symptom, not a cause
                assert last_err is not None
                last_err = TransportError(
                    f"{str(last_err)} (deadline budget {budget:.1f}s "
                    f"exhausted after {attempt} attempt(s))",
                    status=last_err.status, body=last_err.body)
                break
            self._note_retry(method, path, attempt, attempt_started,
                             last_err, reason or "retry")
            self._sleep(sleep_s)
        if last_err is None:
            # no attempt ever ran (degenerate budget): release a half-open
            # probe slot we may have claimed in allow() — but record NO
            # failure; the API was never contacted, and a client-side
            # misconfiguration must not walk the breaker toward open
            if self.breaker is not None:
                self.breaker.release_probe()
            last_err = TransportError(
                f"{method} {path}: deadline budget {budget:.1f}s exhausted "
                f"before any attempt", status=0)
        raise last_err
