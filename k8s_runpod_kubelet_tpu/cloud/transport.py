"""HTTP transport with auth, timeouts and bounded retry.

Analog of the reference's REST plumbing (runpod_client.go:742-770 makeRESTRequest:
Bearer auth, 30s default / 60s deploy timeouts; retry w/ linear backoff x3
:275-307). stdlib-only so the control plane has zero third-party deps.
"""

from __future__ import annotations

import json
import logging
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Optional

log = logging.getLogger(__name__)

DEFAULT_TIMEOUT_S = 30.0
DEPLOY_TIMEOUT_S = 60.0
MAX_RETRIES = 3
BACKOFF_BASE_S = 0.5  # sleep 0.5s * attempt, as the reference does (:302)


class TransportError(Exception):
    """A request failed after retries. ``status`` is the last HTTP status (0 = network)."""

    def __init__(self, message: str, status: int = 0, body: str = ""):
        super().__init__(message)
        self.status = status
        self.body = body


class HttpTransport:
    """Tiny JSON-over-HTTP client: request(), with bearer auth and retry on 5xx/network.

    4xx responses are NOT retried (they are deterministic), mirroring the
    reference's retry helper which only loops on transport errors and 5xx —
    EXCEPT 401 when a refreshable ``token_provider`` is set: GCP access
    tokens expire hourly (unlike the reference's immortal API key,
    runpod_client.go:144), so one 401 triggers provider.invalidate() and a
    single re-issue with a fresh token before giving up.

    ``token_provider`` is any callable returning the current bearer token
    (see cloud/gcp_auth.py); an optional ``invalidate()`` attribute enables
    the 401 refresh path. A plain ``token`` string still works and wins if
    both are given (explicit beats ambient).
    """

    def __init__(
        self,
        base_url: str,
        token: str = "",
        token_provider: Optional[Callable[[], str]] = None,
        timeout_s: float = DEFAULT_TIMEOUT_S,
        max_retries: int = MAX_RETRIES,
        sleep: Callable[[float], None] = time.sleep,
        user_agent: str = "tpu-virtual-kubelet/0.1",
    ):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.token_provider = token_provider
        self.timeout_s = timeout_s
        self.max_retries = max_retries
        self._sleep = sleep
        self.user_agent = user_agent

    def _bearer(self) -> str:
        if self.token:
            return self.token
        if self.token_provider is not None:
            return self.token_provider()
        return ""

    def request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        timeout_s: Optional[float] = None,
        expect_status: tuple[int, ...] = (200,),
        max_retries: Optional[int] = None,
    ) -> Any:
        """Issue a JSON request; returns the decoded JSON body (or None for empty).

        ``max_retries`` overrides the transport-wide attempt count for calls
        whose caller would rather fail fast than block (e.g. the quota read
        that rides the readiness probe's ping path)."""
        url = self.base_url + path
        data = json.dumps(body).encode() if body is not None else None
        retries = self.max_retries if max_retries is None else max_retries
        last_err: Optional[TransportError] = None
        auth_retried = False
        attempt = 0
        while attempt < retries:
            attempt += 1
            req = urllib.request.Request(url, data=data, method=method)
            req.add_header("Content-Type", "application/json")
            req.add_header("User-Agent", self.user_agent)
            try:
                bearer = self._bearer()
            except Exception as e:
                # transient token-fetch failure (metadata-server blip):
                # rides the same retry/backoff and keeps the TransportError
                # contract every caller catches
                last_err = TransportError(
                    f"{method} {path}: token fetch failed: {e}", status=0)
                if attempt < retries:
                    self._sleep(BACKOFF_BASE_S * attempt)
                    log.debug("retrying %s %s (attempt %d): %s",
                              method, path, attempt + 1, last_err)
                continue
            if bearer:
                req.add_header("Authorization", f"Bearer {bearer}")
            try:
                with urllib.request.urlopen(req, timeout=timeout_s or self.timeout_s) as resp:
                    raw = resp.read()
                    if resp.status not in expect_status:
                        raise TransportError(
                            f"{method} {path}: unexpected status {resp.status}",
                            status=resp.status, body=raw.decode(errors="replace"))
                    return json.loads(raw) if raw else None
            except urllib.error.HTTPError as e:
                body_text = e.read().decode(errors="replace")
                if e.code in expect_status:
                    return json.loads(body_text) if body_text else None
                last_err = TransportError(
                    f"{method} {path}: HTTP {e.code}", status=e.code, body=body_text)
                if e.code == 401 and not auth_retried and \
                        hasattr(self.token_provider, "invalidate") and \
                        not self.token:
                    # expired/revoked token: refresh once, re-issue now
                    # (does not consume a backoff-retry slot)
                    auth_retried = True
                    attempt -= 1
                    self.token_provider.invalidate()
                    log.info("401 on %s %s — refreshing bearer token",
                             method, path)
                    continue
                if e.code < 500:  # deterministic failure — don't retry
                    raise last_err
            except (urllib.error.URLError, TimeoutError, ConnectionError, OSError) as e:
                last_err = TransportError(f"{method} {path}: {e}", status=0)
            if attempt < retries:
                self._sleep(BACKOFF_BASE_S * attempt)
                log.debug("retrying %s %s (attempt %d): %s", method, path, attempt + 1, last_err)
        assert last_err is not None
        raise last_err
