"""Typed Cloud TPU API surface: states, catalog, queued-resource records.

TPU-native redesign of the reference's cloud data model:
- state enum          ~ runpod_client.go:55-64 (RUNNING/STARTING/TERMINATING/
                        TERMINATED/NOT_FOUND/EXITED) — remapped onto QueuedResource
                        lifecycle states, which include queueing (WAITING_FOR_RESOURCES)
                        and preemption (SUSPENDED), both absent from the reference.
- accelerator catalog ~ runpod_client.go:431-520 (GetGPUTypes price-filtered GPU
                        selection) — replaced by a generation+topology selector, since
                        TPU capacity is sold as whole slices, not per-GPU prices.
- DetailedStatus      ~ runpod_client.go:111-134 (DetailedStatus/RuntimeInfo with
                        portMappings and exit info) — replaced by per-worker runtime
                        info, because a slice has N workers that must be aggregated.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Optional

from ..generations import GENERATIONS


class QueuedResourceState(str, enum.Enum):
    """Lifecycle of a Cloud TPU queued resource (plus synthetic terminal states).

    Mapping to the reference's 6-state enum (runpod_client.go:55-64):
      ACCEPTED / WAITING_FOR_RESOURCES / PROVISIONING -> STARTING
      ACTIVE                                          -> RUNNING
      SUSPENDING / DELETING                           -> TERMINATING
      SUSPENDED                                       -> TERMINATED (preempted; common
                                                        on TPU, edge-case on RunPod)
      FAILED                                          -> EXITED (with failure)
      NOT_FOUND                                       -> NOT_FOUND
      EXITED is synthesized when the *workload* on an ACTIVE slice finishes
      (per-worker exit aggregation) — see provider/status.py.
    """

    ACCEPTED = "ACCEPTED"
    WAITING_FOR_RESOURCES = "WAITING_FOR_RESOURCES"
    PROVISIONING = "PROVISIONING"
    ACTIVE = "ACTIVE"
    SUSPENDING = "SUSPENDING"
    SUSPENDED = "SUSPENDED"
    DELETING = "DELETING"
    FAILED = "FAILED"
    NOT_FOUND = "NOT_FOUND"  # synthetic: GET returned 404

    @property
    def is_terminal(self) -> bool:
        return self in (
            QueuedResourceState.SUSPENDED,
            QueuedResourceState.FAILED,
            QueuedResourceState.NOT_FOUND,
        )

    @property
    def is_provisioning(self) -> bool:
        return self in (
            QueuedResourceState.ACCEPTED,
            QueuedResourceState.WAITING_FOR_RESOURCES,
            QueuedResourceState.PROVISIONING,
        )


@dataclasses.dataclass(frozen=True)
class AcceleratorType:
    """One row of the accelerator catalog (replaces the reference's GPUType)."""

    name: str              # e.g. "v5litepod-16"
    generation: str        # e.g. "v5e"
    chips: int             # total chips in the slice
    hosts: int             # TPU VM workers (gang size)
    chips_per_host: int
    topology: str          # e.g. "4x4"
    hbm_gib_per_chip: int
    default_runtime: str   # e.g. "v2-alpha-tpuv5-lite"
    cost_per_chip_hr: float  # USD, on-demand list price (cost visibility parity:
                             # reference annotates runpod.io/cost-per-hr, kubelet.go:524)

    @property
    def cost_per_hr(self) -> float:
        return round(self.cost_per_chip_hr * self.chips, 4)


def _gen(generation: str, prefix: str, runtime: str, chips_per_host: int,
         hbm: int, slices: list[tuple[int, str]]) -> list[AcceleratorType]:
    # $/chip-hr comes from the shared generations table (ISSUE 19) so the
    # catalog, the scheduler's goodput-per-dollar math and bench all price
    # a chip identically
    cost = GENERATIONS[generation].cost_per_chip_hr
    out = []
    for chips, topology in slices:
        hosts = max(1, chips // chips_per_host)
        out.append(AcceleratorType(
            name=f"{prefix}-{chips}", generation=generation, chips=chips,
            hosts=hosts, chips_per_host=chips_per_host, topology=topology,
            hbm_gib_per_chip=hbm, default_runtime=runtime, cost_per_chip_hr=cost))
    return out


# Static catalog of the TPU fleet the virtual node can offer. The fake API server
# serves exactly this catalog; a real deployment would overlay live availability.
ACCELERATOR_CATALOG: dict[str, AcceleratorType] = {
    a.name: a
    for a in (
        _gen("v4", "v4", "tpu-vm-v4-base", 4, 32, [
            (8, "2x2x1"), (16, "2x2x2"), (32, "2x2x4"), (64, "2x4x4"),
            (128, "4x4x4"), (256, "4x4x8"), (512, "4x8x8"),
        ])
        + _gen("v5e", "v5litepod", "v2-alpha-tpuv5-lite", 4, 16, [
            (1, "1x1"), (4, "2x2"), (8, "2x4"), (16, "4x4"),
            (32, "4x8"), (64, "8x8"), (128, "8x16"), (256, "16x16"),
        ])
        + _gen("v5p", "v5p", "v2-alpha-tpuv5", 4, 95, [
            (8, "2x2x1"), (16, "2x2x2"), (32, "2x2x4"), (64, "2x4x4"),
            (128, "4x4x4"), (256, "4x4x8"), (512, "4x8x8"),
        ])
        + _gen("v6e", "v6e", "v2-alpha-tpuv6e", 4, 32, [
            (1, "1x1"), (4, "2x2"), (8, "2x4"), (16, "4x4"),
            (32, "4x8"), (64, "8x8"), (128, "8x16"), (256, "16x16"),
        ])
    )
}

# v5e single-host slices have special chips_per_host: v5litepod-1 is 1 chip / 1 host,
# v5litepod-4 is 4 chips / 1 host, v5litepod-8 is 8 chips / 1 host (2 boards).
for _name, _hosts, _cph in (("v5litepod-1", 1, 1), ("v5litepod-4", 1, 4),
                            ("v5litepod-8", 1, 8), ("v6e-1", 1, 1),
                            ("v6e-4", 1, 4), ("v6e-8", 1, 8)):
    _a = ACCELERATOR_CATALOG[_name]
    ACCELERATOR_CATALOG[_name] = dataclasses.replace(_a, hosts=_hosts, chips_per_host=_cph)


def lookup_accelerator(name: str) -> Optional[AcceleratorType]:
    return ACCELERATOR_CATALOG.get(name)


def select_accelerator(
    *,
    chips: Optional[int] = None,
    generation: Optional[str] = None,
    topology: Optional[str] = None,
    min_hbm_gib: Optional[int] = None,
    max_cost_per_hr: Optional[float] = None,
    limit: int = 5,
) -> list[AcceleratorType]:
    """Generation+topology selector.

    Replaces the reference's GPU selection (runpod_client.go:465-509: filter by
    cloudType/price/minRAM, sort by price, take top 5). Filters the catalog by the
    pod's requested chip count / generation / topology / HBM floor / cost ceiling,
    sorts by (cost, chips) ascending so the cheapest satisfying slice wins, and
    returns up to ``limit`` candidates.
    """
    out = []
    for a in ACCELERATOR_CATALOG.values():
        if chips is not None and a.chips != chips:
            continue
        if generation is not None and a.generation != generation:
            continue
        if topology is not None and a.topology != topology:
            continue
        if min_hbm_gib is not None and a.hbm_gib_per_chip < min_hbm_gib:
            continue
        if max_cost_per_hr is not None and a.cost_per_hr > max_cost_per_hr:
            continue
        out.append(a)
    out.sort(key=lambda a: (a.cost_per_hr, a.chips))
    return out[:limit]


@dataclasses.dataclass
class WorkerRuntimeInfo:
    """Per-worker workload state (analog of RuntimeInfo, runpod_client.go:128-134)."""

    worker_id: int
    hostname: str = ""
    internal_ip: str = ""
    healthy: bool = True
    workload_running: bool = False
    exit_code: Optional[int] = None
    exit_message: str = ""
    started_at: Optional[float] = None
    finished_at: Optional[float] = None


@dataclasses.dataclass
class TpuWorker:
    """One TPU VM of a slice."""

    worker_id: int
    hostname: str
    internal_ip: str
    external_ip: str = ""
    state: str = "READY"  # CREATING / READY / UNHEALTHY / PREEMPTED


@dataclasses.dataclass
class QueuedResource:
    """A queued-resource record as returned by the cloud API."""

    name: str
    accelerator_type: str
    runtime_version: str
    state: QueuedResourceState
    zone: str = "us-central2-b"
    state_message: str = ""
    spot: bool = False
    reservation: str = ""
    workers: list[TpuWorker] = dataclasses.field(default_factory=list)
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    create_time: float = dataclasses.field(default_factory=time.time)

    @property
    def accelerator(self) -> Optional[AcceleratorType]:
        return lookup_accelerator(self.accelerator_type)


@dataclasses.dataclass
class DetailedStatus:
    """Aggregated slice + workload status for the reconcile loop.

    Analog of the reference's DetailedStatus (runpod_client.go:111-126,
    GetDetailedPodStatus :773-818), generalized from one container's port mappings
    to N workers' runtime state. ``ports`` preserved for readiness parity.
    """

    resource: QueuedResource
    runtime: list[WorkerRuntimeInfo] = dataclasses.field(default_factory=list)
    ports: dict[int, int] = dataclasses.field(default_factory=dict)  # private->public

    @property
    def all_workers_healthy(self) -> bool:
        if not self.runtime:
            return False
        return all(w.healthy for w in self.runtime)

    @property
    def all_exited(self) -> bool:
        return bool(self.runtime) and all(w.exit_code is not None for w in self.runtime)

    @property
    def max_exit_code(self) -> Optional[int]:
        codes = [w.exit_code for w in self.runtime if w.exit_code is not None]
        return max(codes) if codes else None
