"""FakeWorkerHost: a docker-lite worker-VM simulator for hermetic tests.

Where InMemoryWorkerTransport replays canned strings, this transport actually
*models* each TPU VM's container state, understanding the command grammar the
SSH workload backend issues (cloud/workload_backend.py):

  sh -c "docker rm -f NAME ... ; docker run -d --name NAME ... IMAGE CMD..."
  docker inspect --format '...' NAME
  docker logs [--tail N] NAME        (via .logs(), as SshWorkerTransport does)
  docker exec NAME CMD...

so the full real-cloud lifecycle — gang launch over "SSH", per-worker docker
state aggregation, worker death, exit codes — runs without a cloud or a
daemon. Fault injection: ``kill_worker`` (VM unreachable), ``finish``
(container exits), ``fail_next_run`` (docker run errors once).
"""

from __future__ import annotations

import dataclasses
import shlex
import threading
import time
from typing import Optional

from .exec import WorkerExecError, WorkerTransport

_UNREACHABLE_EXIT = 255  # ssh's own exit code when the host is unreachable


@dataclasses.dataclass
class _Container:
    name: str
    image: str
    env: dict[str, str]
    command: list[str]
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    status: str = "running"            # running | exited | dead
    exit_code: int = 0
    started_at: float = dataclasses.field(default_factory=time.time)
    log_lines: list[str] = dataclasses.field(default_factory=list)


class FakeWorkerHost(WorkerTransport):
    def __init__(self):
        self.lock = threading.RLock()
        # (qr_name, worker_id) -> {container_name: _Container}
        self.hosts: dict[tuple[str, int], dict[str, _Container]] = {}
        self.dead_workers: set[tuple[str, int]] = set()
        self.fail_next_run: set[tuple[str, int]] = set()
        self.calls: list[tuple[str, int, list[str]]] = []

    # -- fault injection / assertions ------------------------------------------

    def kill_worker(self, qr_name: str, worker_id: int):
        """VM becomes unreachable (maintenance/preemption); its containers
        die with it."""
        with self.lock:
            self.dead_workers.add((qr_name, worker_id))

    def revive_worker(self, qr_name: str, worker_id: int):
        """Capacity returned (host_loss window closed): the replacement VM
        is reachable again, but as a FRESH host — whatever containers the
        dead VM ran are gone; the kubelet's elastic grow path relaunches
        the gang on it. The natural partner of kill_worker for host_loss
        chaos windows (cloud/faults.py)."""
        with self.lock:
            self.dead_workers.discard((qr_name, worker_id))
            self.hosts.pop((qr_name, worker_id), None)

    def host_loss_hook(self, qr_name: str, worker_id: int, lost: bool):
        """FaultPlan bridge: wire as ``fake_service.host_loss_hook`` so a
        host_loss window kills/revives the docker-lite VM in lockstep with
        the fake cloud's worker records (the SSH-path elastic soak)."""
        if lost:
            self.kill_worker(qr_name, worker_id)
        else:
            self.revive_worker(qr_name, worker_id)

    def finish(self, qr_name: str, exit_codes: Optional[list[int]] = None,
               container: str = "workload"):
        """Workload exits on every worker (exit_codes[i] or 0)."""
        with self.lock:
            workers = sorted(k for k in self.hosts if k[0] == qr_name)
            for i, key in enumerate(workers):
                c = self.hosts[key].get(container)
                if c and c.status == "running":
                    c.status = "exited"
                    c.exit_code = (exit_codes[i] if exit_codes
                                   and i < len(exit_codes) else 0)

    def container(self, qr_name: str, worker_id: int,
                  name: str = "workload") -> Optional[_Container]:
        with self.lock:
            return self.hosts.get((qr_name, worker_id), {}).get(name)

    def append_log(self, qr_name: str, worker_id: int, line: str,
                   container: str = "workload"):
        with self.lock:
            c = self.container(qr_name, worker_id, container)
            if c:
                c.log_lines.append(line)

    # -- the training-telemetry line protocol (ISSUE 5) --------------------------
    # The fake host speaks the same wire format train_main emits, so the
    # kubelet's log-scrape path (GangExecutor.last_in_logs + parse_telemetry)
    # is exercised verbatim by the straggler soak.

    def heartbeat(self, qr_name: str, worker_id: int, step: int,
                  step_time_s: float):
        """Worker logs one TPU_STEP_HEARTBEAT protocol line."""
        from ..workloads.telemetry import format_heartbeat
        self.append_log(qr_name, worker_id,
                        format_heartbeat(worker_id, step, step_time_s))

    def telemetry(self, qr_name: str, payload: dict, worker_id: int = 0):
        """Worker-0 logs one TPU_TELEMETRY state line (the kubelet's
        scrape target)."""
        from ..workloads.telemetry import format_telemetry
        self.append_log(qr_name, worker_id, format_telemetry(payload))

    # -- the docker-lite grammar ------------------------------------------------

    def host_run(self, qr, worker_id, cmd, timeout_s=60.0):
        """Host-level command on the VM (the workload backend's surface)."""
        key = (qr.name, worker_id)
        with self.lock:
            self.calls.append((qr.name, worker_id, list(cmd)))
            if key in self.dead_workers:
                raise WorkerExecError(f"ssh: connect to worker {worker_id}: "
                                      "No route to host",
                                      exit_code=_UNREACHABLE_EXIT)
            host = self.hosts.setdefault(key, {})
            if cmd[:2] == ["sh", "-c"]:
                return self._shell(key, host, cmd[2])
            if cmd[:2] == ["docker", "inspect"]:
                return self._inspect(host, cmd[-1])
            if cmd[:2] == ["docker", "exec"]:
                return self._exec(host, cmd[2], cmd[3:])
            return ""  # unknown command: succeed silently, like a quiet shell

    def run(self, qr, worker_id, cmd, timeout_s=60.0):
        """In-container exec (the kubelet API's /run surface)."""
        key = (qr.name, worker_id)
        with self.lock:
            self.calls.append((qr.name, worker_id, list(cmd)))
            if key in self.dead_workers:
                raise WorkerExecError("ssh: No route to host",
                                      exit_code=_UNREACHABLE_EXIT)
            host = self.hosts.setdefault(key, {})
            return self._exec(host, "workload", cmd)

    def _shell(self, key, host, script: str) -> str:
        out = ""
        for segment in script.split(";"):
            toks = shlex.split(segment)
            # strip trailing `|| true` / redirections appended by the backend
            toks = [t for t in toks
                    if t not in ("||", "true") and not t.startswith(">")
                    and t not in ("2>&1",)]
            if toks[:3] == ["docker", "rm", "-f"]:
                host.pop(toks[3], None)
            elif toks[:2] == ["docker", "run"]:
                out = self._docker_run(key, host, toks)
        return out

    def _docker_run(self, key, host, toks: list[str]) -> str:
        if key in self.fail_next_run:
            self.fail_next_run.discard(key)
            raise WorkerExecError("docker: Error response from daemon: "
                                  "failed to create task", exit_code=125)
        env: dict[str, str] = {}
        labels: dict[str, str] = {}
        name = "workload"
        i = 2
        while i < len(toks):
            t = toks[i]
            if t == "-e" and i + 1 < len(toks):
                k, _, v = toks[i + 1].partition("=")
                env[k] = v
                i += 2
            elif t in ("-l", "--label") and i + 1 < len(toks):
                k, _, v = toks[i + 1].partition("=")
                labels[k] = v
                i += 2
            elif t == "--name" and i + 1 < len(toks):
                name = toks[i + 1]
                i += 2
            elif t.startswith("-"):
                i += 1
            else:
                break
        if i >= len(toks):
            raise WorkerExecError("docker run: no image given", exit_code=125)
        image, command = toks[i], toks[i + 1:]
        if name in host:
            raise WorkerExecError(
                f'docker: Error response from daemon: Conflict. The container '
                f'name "/{name}" is already in use', exit_code=125)
        host[name] = _Container(name=name, image=image, env=env,
                                labels=labels, command=command)
        return "deadbeef" + name  # container id

    def _inspect(self, host, name: str) -> str:
        c = host.get(name)
        if c is None:
            raise WorkerExecError(f"Error: No such object: {name}", exit_code=1)
        ports = c.labels.get("tpu-ports", "-")
        return f"{c.status} {c.exit_code} {c.started_at} {ports}\n"

    def _exec(self, host, name: str, cmd: list[str]) -> str:
        c = host.get(name)
        if c is None or c.status != "running":
            raise WorkerExecError(f"container {name} is not running", exit_code=1)
        return f"exec:{' '.join(cmd)}\n"

    def stream_exec(self, qr, worker_id, cmd, tty=False):
        """Interactive exec simulation: requires a running workload container
        on the worker, then runs the command as a LOCAL subprocess so the
        WebSocket bridge is exercised against real pipes/exit codes."""
        import subprocess
        key = (qr.name, worker_id)
        with self.lock:
            if key in self.dead_workers:
                raise WorkerExecError("ssh: No route to host",
                                      exit_code=_UNREACHABLE_EXIT)
            c = self.hosts.get(key, {}).get("workload")
            if c is None or c.status != "running":
                raise WorkerExecError("container workload is not running",
                                      exit_code=1)
        return subprocess.Popen(cmd, stdin=subprocess.PIPE,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE)

    def logs(self, qr, worker_id, tail_lines=None):
        key = (qr.name, worker_id)
        with self.lock:
            if key in self.dead_workers:
                raise WorkerExecError("ssh: No route to host",
                                      exit_code=_UNREACHABLE_EXIT)
            c = self.hosts.get(key, {}).get("workload")
            if c is None:
                raise WorkerExecError("Error: No such container: workload",
                                      exit_code=1)
            lines = c.log_lines[-tail_lines:] if tail_lines else c.log_lines
            return "\n".join(lines) + ("\n" if lines else "")
