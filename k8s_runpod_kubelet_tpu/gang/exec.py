"""Per-worker exec/log transport.

Where RunPod offered no exec path (the reference stubs RunInContainer and
GetContainerLogs, kubelet.go:2027-2066), TPU VMs are SSH-able. The kubelet API
server's real /containerLogs and /run endpoints route through a GangExecutor,
which fans a command out to all (or one) of a slice's workers.

Transports:
- SshWorkerTransport: shells out to ``ssh`` (TPU VMs with OS Login / metadata
  keys). Used in real deployments.
- InMemoryWorkerTransport: deterministic fake for hermetic tests.
"""

from __future__ import annotations

import logging
import re
import shlex
import subprocess
import threading
from typing import Optional

from ..cloud.types import QueuedResource

log = logging.getLogger(__name__)


class WorkerExecError(Exception):
    def __init__(self, message: str, exit_code: int = 1, output: str = ""):
        super().__init__(message)
        self.exit_code = exit_code
        self.output = output


class WorkerTransport:
    """Protocol: run a command on one worker of a slice."""

    def run(self, qr: QueuedResource, worker_id: int, cmd: list[str],
            timeout_s: float = 60.0) -> str:
        """Run a command INSIDE the workload container (kubectl-exec shape)."""
        raise NotImplementedError

    def host_run(self, qr: QueuedResource, worker_id: int, cmd: list[str],
                 timeout_s: float = 60.0) -> str:
        """Run a command on the worker VM itself — the surface the SSH
        workload backend drives docker through (cloud/workload_backend.py)."""
        raise NotImplementedError

    def stream_exec(self, qr: QueuedResource, worker_id: int, cmd: list[str],
                    tty: bool = False):
        """Interactive exec in the workload container: returns a Popen-like
        object with binary ``.stdin``/``.stdout`` pipes, ``.poll()``,
        ``.wait()`` and ``.kill()`` — the kubectl-exec streaming surface
        (node/api_server.py bridges it over WebSocket)."""
        raise NotImplementedError

    def logs(self, qr: QueuedResource, worker_id: int,
             tail_lines: Optional[int] = None) -> str:
        """Workload container logs on one worker."""
        raise NotImplementedError


class SshWorkerTransport(WorkerTransport):
    """SSH to the TPU VM; the workload runs as container 'workload' under docker."""

    def __init__(self, user: str = "tpu", ssh_opts: Optional[list[str]] = None,
                 container_name: str = "workload",
                 killable_exec: bool = True):
        self.user = user
        self.ssh_opts = ssh_opts or ["-o", "StrictHostKeyChecking=no",
                                     "-o", "ConnectTimeout=10"]
        self.container_name = container_name
        # non-tty execs wrap in `sh -c` (pid recording for remote_kill);
        # set False for SHELL-LESS workload images (distroless/scratch) to
        # keep the plain direct exec — those lose disconnect-kill, like
        # kubectl itself without a pty
        self.killable_exec = killable_exec

    def _target(self, qr: QueuedResource, worker_id: int) -> str:
        w = qr.workers[worker_id]
        return f"{self.user}@{w.external_ip or w.internal_ip or w.hostname}"

    def _ssh(self, qr: QueuedResource, worker_id: int, remote_cmd: str,
             timeout_s: float) -> str:
        argv = ["ssh", *self.ssh_opts, self._target(qr, worker_id), remote_cmd]
        try:
            res = subprocess.run(argv, capture_output=True, text=True, timeout=timeout_s)
        except subprocess.TimeoutExpired as e:
            raise WorkerExecError(f"ssh to worker {worker_id} timed out") from e
        if res.returncode != 0:
            raise WorkerExecError(
                f"worker {worker_id}: exit {res.returncode}: {res.stderr[:500]}",
                exit_code=res.returncode, output=res.stdout)
        return res.stdout

    def run(self, qr, worker_id, cmd, timeout_s=60.0):
        inner = " ".join(shlex.quote(c) for c in cmd)
        return self._ssh(qr, worker_id,
                         f"docker exec {self.container_name} {inner}", timeout_s)

    def host_run(self, qr, worker_id, cmd, timeout_s=60.0):
        return self._ssh(qr, worker_id,
                         " ".join(shlex.quote(c) for c in cmd), timeout_s)

    def stream_exec(self, qr, worker_id, cmd, tty=False):
        inner = " ".join(shlex.quote(c) for c in cmd)
        flags = "-it" if tty else "-i"
        argv = ["ssh", *self.ssh_opts]
        remote_kill = None
        if tty or not self.killable_exec:
            if tty:
                argv.append("-tt")  # force a remote pty for the container
            # pty sessions need no explicit kill: ssh teardown hangs up the
            # remote pty and the kernel SIGHUPs the process group.
            # killable_exec=False: plain direct exec for shell-less images
            # (no disconnect-kill — kubectl-without-pty parity).
            remote_cmd = f"docker exec {flags} {self.container_name} {inner}"
        else:
            # NON-tty: killing the local ssh leaves the remote process
            # running (sshd keeps it; no pty to hang up). Record its pid in
            # the container and kill through a SECOND short exec when the
            # client goes away — the piece kubectl itself lacks without a
            # worker agent (r2 weak-list item 8).
            import uuid
            pidfile = f"/tmp/.tpu-exec-{uuid.uuid4().hex[:12]}.pid"
            # prune pidfiles of DEAD prior execs first (kill -0 = liveness
            # probe): normal exits never reap remotely (see api_server), so
            # this lazy sweep is what keeps /tmp bounded; live concurrent
            # execs keep their files
            prune = ("for f in /tmp/.tpu-exec-*.pid; do "
                     "kill -0 \"$(cat \"$f\" 2>/dev/null)\" 2>/dev/null "
                     "|| rm -f \"$f\"; done; ")
            # write-then-rename: the pidfile appears ATOMICALLY, so a
            # concurrent exec's prune can never cat a truncated-but-
            # unwritten file and reap a live session's record
            payload = (f"{prune}echo $$ > {pidfile}.tmp && "
                       f"mv {pidfile}.tmp {pidfile}; exec {inner}")
            remote_cmd = (f"docker exec {flags} {self.container_name} "
                          f"sh -c {shlex.quote(payload)}")

            def remote_kill(qr=qr, worker_id=worker_id, pidfile=pidfile):
                # called only for ABORTED sessions. Wait briefly for the
                # pidfile: a client that drops within the first second can
                # beat the wrapper's `echo $$` over the other ssh session —
                # without the poll, the process this feature exists to kill
                # would survive. Then group kill first (covers forked
                # children when the pid leads a group), single-pid fallback.
                reap = (f"i=0; while [ ! -f {pidfile} ] && [ $i -lt 20 ]; "
                        f"do sleep 0.1; i=$((i+1)); done; "
                        f"p=$(cat {pidfile} 2>/dev/null); "
                        f"[ -n \"$p\" ] && "
                        f"{{ kill -TERM -- -$p 2>/dev/null || "
                        f"kill -TERM $p 2>/dev/null; }}; "
                        f"rm -f {pidfile}")
                try:
                    self._ssh(qr, worker_id,
                              f"docker exec {self.container_name} "
                              f"sh -c {shlex.quote(reap)}", timeout_s=10.0)
                except Exception:  # noqa: BLE001 — best-effort cleanup:
                    pass           # worker gone / process already exited
        argv += [self._target(qr, worker_id), remote_cmd]
        # stderr stays a separate pipe: the channel protocol has a dedicated
        # STDERR channel, and ssh's own diagnostics (host-key warnings) must
        # never interleave into a binary stdout stream
        proc = subprocess.Popen(argv, stdin=subprocess.PIPE,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE)
        proc.remote_kill = remote_kill
        return proc

    def logs(self, qr, worker_id, tail_lines=None):
        tail = f" --tail {tail_lines}" if tail_lines else ""
        return self._ssh(qr, worker_id,
                         f"docker logs{tail} {self.container_name}", timeout_s=30.0)


class InMemoryWorkerTransport(WorkerTransport):
    """Test fake: scripted outputs + recorded calls, per (slice, worker)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.calls: list[tuple[str, int, list[str]]] = []
        self._logs: dict[tuple[str, int], list[str]] = {}
        self.responses: dict[str, str] = {}  # cmd[0] -> canned stdout
        self.fail_workers: set[tuple[str, int]] = set()

    def append_log(self, qr_name: str, worker_id: int, line: str):
        with self.lock:
            self._logs.setdefault((qr_name, worker_id), []).append(line)

    def run(self, qr, worker_id, cmd, timeout_s=60.0):
        with self.lock:
            self.calls.append((qr.name, worker_id, list(cmd)))
            if (qr.name, worker_id) in self.fail_workers:
                raise WorkerExecError(f"worker {worker_id} unreachable", exit_code=255)
            return self.responses.get(cmd[0] if cmd else "", "")

    def host_run(self, qr, worker_id, cmd, timeout_s=60.0):
        return self.run(qr, worker_id, cmd, timeout_s)

    def logs(self, qr, worker_id, tail_lines=None):
        with self.lock:
            if (qr.name, worker_id) in self.fail_workers:
                raise WorkerExecError(f"worker {worker_id} unreachable", exit_code=255)
            lines = self._logs.get((qr.name, worker_id), [])
            if tail_lines:
                lines = lines[-tail_lines:]
            return "\n".join(lines) + ("\n" if lines else "")


class GangExecutor:
    """Fan-out over a slice's workers with all-or-nothing semantics."""

    def __init__(self, transport: WorkerTransport):
        self.transport = transport

    def run_on_worker(self, qr: QueuedResource, worker_id: int, cmd: list[str],
                      timeout_s: float = 60.0, host: bool = False) -> str:
        if not 0 <= worker_id < len(qr.workers):
            raise WorkerExecError(f"slice {qr.name} has no worker {worker_id}")
        fn = self.transport.host_run if host else self.transport.run
        return fn(qr, worker_id, cmd, timeout_s)

    def stream_exec(self, qr: QueuedResource, worker_id: int, cmd: list[str],
                    tty: bool = False):
        if not 0 <= worker_id < len(qr.workers):
            raise WorkerExecError(f"slice {qr.name} has no worker {worker_id}")
        return self.transport.stream_exec(qr, worker_id, cmd, tty=tty)

    def run_on_all(self, qr: QueuedResource, cmd: list[str],
                   timeout_s: float = 60.0, host: bool = False) -> dict[int, str]:
        """Run the SAME command on every worker concurrently; raises if ANY
        worker fails (gang semantics — a partial launch is a failed launch)."""
        return self.run_per_worker(qr, {w.worker_id: cmd for w in qr.workers},
                                   timeout_s=timeout_s, host=host)

    def run_per_worker(self, qr: QueuedResource, cmds: dict[int, list[str]],
                       timeout_s: float = 60.0, host: bool = False
                       ) -> dict[int, str]:
        """Run a per-worker command map concurrently, all-or-nothing (the
        gang-launch shape: same program, per-worker env baked into each
        command)."""
        results: dict[int, str] = {}
        errors: dict[int, Exception] = {}
        fn = self.transport.host_run if host else self.transport.run

        def one(i: int):
            try:
                results[i] = fn(qr, i, cmds[i], timeout_s)
            except Exception as e:  # noqa: BLE001
                errors[i] = e

        threads = {w.worker_id: threading.Thread(target=one, args=(w.worker_id,),
                                                 daemon=True)
                   for w in qr.workers if w.worker_id in cmds}
        for t in threads.values():
            t.start()
        for t in threads.values():
            t.join(timeout=timeout_s + 5)
        for wid, t in threads.items():
            # a worker that outlived the join deadline is a failure, not a
            # silent omission — all-or-nothing means ALL accounted for
            if t.is_alive() and wid not in results and wid not in errors:
                errors[wid] = WorkerExecError(f"worker {wid} still running after "
                                              f"{timeout_s + 5:.0f}s deadline")
        if errors:
            detail = "; ".join(f"w{i}: {e}" for i, e in sorted(errors.items()))
            raise WorkerExecError(
                f"gang command failed on {len(errors)}/{len(qr.workers)} workers: {detail}")
        return results

    def find_in_logs(self, qr: QueuedResource, pattern: str,
                     worker_id: int = 0, tail_lines: int = 500
                     ) -> Optional["re.Match"]:
        """Search one worker's recent logs for a regex — best-effort (None on
        any transport failure or no match). Used by the reconcile loop's
        preemption-recovery event to read the checkpoint step a relaunched
        workload resumed from; observability only, never control flow."""
        if not qr.workers or not 0 <= worker_id < len(qr.workers):
            return None
        try:
            body = self.transport.logs(qr, worker_id, tail_lines)
        except Exception as e:  # noqa: BLE001 — worker may be mid-boot/gone
            log.debug("log probe on %s/w%d failed: %s", qr.name, worker_id, e)
            return None
        return re.search(pattern, body)

    def last_in_logs(self, qr: QueuedResource, pattern: str,
                     worker_id: int = 0, tail_lines: int = 500
                     ) -> Optional["re.Match"]:
        """Like find_in_logs but the LAST match wins — the shape telemetry
        scrapes need (a worker logs one TPU_TELEMETRY state line per step;
        only the newest describes the pod's current progress)."""
        if not qr.workers or not 0 <= worker_id < len(qr.workers):
            return None
        try:
            body = self.transport.logs(qr, worker_id, tail_lines)
        except Exception as e:  # noqa: BLE001 — worker may be mid-boot/gone
            log.debug("log probe on %s/w%d failed: %s", qr.name, worker_id, e)
            return None
        match = None
        for match in re.finditer(pattern, body):
            pass
        return match

    def logs(self, qr: QueuedResource, worker_id: Optional[int] = None,
             tail_lines: Optional[int] = None) -> str:
        """One worker's logs, or all workers' logs with [worker N] prefixes."""
        if worker_id is not None:
            if not 0 <= worker_id < len(qr.workers):
                raise WorkerExecError(
                    f"slice {qr.name} has no worker {worker_id}")
            return self.transport.logs(qr, worker_id, tail_lines)
        chunks = []
        for w in qr.workers:
            try:
                body = self.transport.logs(qr, w.worker_id, tail_lines)
            except Exception as e:  # noqa: BLE001
                body = f"<logs unavailable: {e}>\n"
            chunks.append(f"==== worker {w.worker_id} ({w.hostname}) ====\n{body}")
        return "".join(chunks)
