"""Gang scheduling: one K8s pod <-> one multi-host TPU slice.

The reference's single biggest capability gap (SURVEY.md §2.4): it maps one pod
to one single-GPU instance and never reads the accelerator count. Here, a pod
requesting ``google.com/tpu: N`` becomes an N-chip slice whose workers are
launched together (all-or-nothing), each with the env that lets XLA form the ICI
mesh and jax.distributed form the DCN ring:

- ``env``:  per-worker env computation (TPU_WORKER_ID, TPU_WORKER_HOSTNAMES,
  coordinator address, megascale/multislice vars).
- ``exec``: per-worker exec/log transport (SSH for real TPU VMs, in-memory fake
  for tests) backing the kubelet API's real logs/exec endpoints.
"""

from .env import compute_worker_env, coordinator_address, DEFAULT_COORDINATOR_PORT
from .exec import WorkerTransport, SshWorkerTransport, InMemoryWorkerTransport, GangExecutor
from .fake_host import FakeWorkerHost

__all__ = [
    "compute_worker_env",
    "coordinator_address",
    "DEFAULT_COORDINATOR_PORT",
    "WorkerTransport",
    "SshWorkerTransport",
    "InMemoryWorkerTransport",
    "FakeWorkerHost",
    "GangExecutor",
]
