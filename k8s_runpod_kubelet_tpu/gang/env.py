"""Per-worker environment injection for gang-launched TPU workloads.

This is the kubelet-side prerequisite for every parallelism strategy in
SURVEY.md §2.4 and §5.7: a slice's workers must all run the same program with a
correctly-formed mesh, which requires each worker to know (a) its identity in
the gang, (b) every peer's address (ICI mesh formation), (c) the jax.distributed
coordinator (DCN / multi-controller runtime), and (d) the multislice (megascale)
coordinator when the job spans slices.

The reference injects nothing (it ships env verbatim to one instance,
runpod_client.go:1334-1342); this module is net-new capability.
"""

from __future__ import annotations

from typing import Optional

from ..cloud.types import QueuedResource, lookup_accelerator

DEFAULT_COORDINATOR_PORT = 8476
DEFAULT_MEGASCALE_PORT = 8080


def coordinator_address(qr: QueuedResource, port: int = DEFAULT_COORDINATOR_PORT,
                        worker_ids: Optional[list[int]] = None) -> str:
    """Worker 0 is the jax.distributed coordinator, by convention. On an
    elastic resize launch over a surviving subset, the LOWEST surviving
    worker takes the role (worker 0 may be the one that died)."""
    workers = qr.workers
    if worker_ids is not None:
        by_id = {w.worker_id: w for w in qr.workers}
        workers = [by_id[i] for i in sorted(worker_ids) if i in by_id]
    host = workers[0].internal_ip or workers[0].hostname if workers else ""
    return f"{host}:{port}"


def compute_worker_env(
    qr: QueuedResource,
    *,
    coordinator_port: int = DEFAULT_COORDINATOR_PORT,
    num_slices: int = 1,
    slice_id: int = 0,
    megascale_coordinator: Optional[str] = None,
    megascale_port: int = DEFAULT_MEGASCALE_PORT,
    telemetry_port: int = 0,
    straggler_factor: float = 0.0,
    stall_timeout_s: float = 0.0,
    worker_ids: Optional[list[int]] = None,
) -> list[dict[str, str]]:
    """Build the per-worker env overlay for a gang launch.

    Returns one dict per worker, merged over the user's workload env by the
    worker agent. Keys follow the conventions GKE/TPU runtimes and
    jax.distributed understand; ``parallel/distributed.py`` consumes the same
    names on the workload side, closing the loop.

    Single-slice: every worker gets the same TPU_WORKER_HOSTNAMES and the
    worker-0 coordinator; ICI needs no config beyond "same program, all hosts".
    Multislice: MEGASCALE_* vars describe the DCN mesh across slices; process
    ids are globally offset so jax sees one flat process space.

    ``worker_ids`` (elastic resize, ISSUE 6): launch over this SUBSET of the
    slice's workers — a shrink after host loss, or a targeted relaunch.
    JAX process ids are renumbered densely over the subset (jax.distributed
    wants a contiguous 0..k-1 process space), the lowest surviving worker
    becomes the coordinator, and TPU_WORKER_ID keeps the PHYSICAL id so
    docker/log targeting still addresses the right VM.
    """
    acc = lookup_accelerator(qr.accelerator_type)
    hosts = qr.workers
    if worker_ids is not None:
        by_id = {w.worker_id: w for w in qr.workers}
        missing = [i for i in worker_ids if i not in by_id]
        if missing:
            raise ValueError(f"slice {qr.name} has no workers {missing}")
        hosts = [by_id[i] for i in sorted(worker_ids)]
    n = len(hosts)
    dense = {w.worker_id: i for i, w in enumerate(hosts)}
    hostnames = ",".join(w.hostname for w in hosts)
    coord = coordinator_address(qr, coordinator_port, worker_ids=worker_ids)
    if megascale_coordinator is None:
        # prefer the hostname: slice 0's default must equal the string other
        # slices put in their tpu.dev/megascale-coordinator annotation (the
        # config4 pattern names slice 0's worker-0 by hostname)
        megascale_coordinator = ((hosts[0].hostname or hosts[0].internal_ip)
                                 if hosts else "")

    envs: list[dict[str, str]] = []
    for w in hosts:
        e = {
            # TPU runtime identity (what GKE's device plugin would inject)
            "TPU_WORKER_ID": str(w.worker_id),
            "TPU_WORKER_HOSTNAMES": hostnames,
            "TPU_ACCELERATOR_TYPE": qr.accelerator_type,
            "TPU_TOPOLOGY": acc.topology if acc else "",
            "TPU_CHIPS_PER_HOST": str(acc.chips_per_host if acc else 0),
            "TPU_RUNTIME_VERSION": qr.runtime_version,
            "TPU_SKIP_MDS_QUERY": "true",  # no GCE metadata server in our pods
            # jax.distributed bootstrap (multi-controller)
            "JAX_COORDINATOR_ADDRESS": coord,
            "JAX_NUM_PROCESSES": str(n * num_slices),
            "JAX_PROCESS_ID": str(slice_id * n + dense[w.worker_id]),
            # slice identity for logging/metrics
            "TPU_SLICE_NAME": qr.name,
            "TPU_ZONE": qr.zone,
        }
        if telemetry_port:
            # training telemetry (ISSUE 5): the GLOBAL process 0 serves
            # /metrics + /debug/train + POST /heartbeat; peers post their
            # per-step heartbeats to TPU_TELEMETRY_ADDRESS. Multislice: that
            # aggregator lives on slice 0's worker-0 — the SAME host the
            # megascale coordinator convention names — NOT this slice's own
            # worker-0 (train_main only starts the server where
            # JAX_PROCESS_ID == 0, so a per-slice address would drop every
            # beat from slices > 0 and false-flag all their hosts stalled)
            tel_host = (megascale_coordinator if num_slices > 1
                        else ((hosts[0].hostname or hosts[0].internal_ip)
                              if hosts else ""))
            e["TPU_TELEMETRY_PORT"] = str(telemetry_port)
            e["TPU_TELEMETRY_ADDRESS"] = f"{tel_host}:{telemetry_port}"
        # the watchdog knobs ride the same injection so the operator's
        # helm/config values actually reach train_main's env-driven defaults
        if straggler_factor > 0:
            e["TPU_STRAGGLER_FACTOR"] = str(straggler_factor)
        if stall_timeout_s > 0:
            e["TPU_STALL_TIMEOUT_S"] = str(stall_timeout_s)
        if num_slices > 1:
            # DCN multislice (MegaScale) wiring — SURVEY.md §5.8
            e.update({
                "MEGASCALE_COORDINATOR_ADDRESS": f"{megascale_coordinator}:{megascale_port}",
                "MEGASCALE_NUM_SLICES": str(num_slices),
                "MEGASCALE_SLICE_ID": str(slice_id),
                "MEGASCALE_PORT": str(megascale_port),
            })
        envs.append(e)
    return envs
