"""Serving workload: HTTP front end over the ServingEngine (config 5).

The pod command for autoscaled inference. Endpoints:
  POST /generate   {"tokens": [...], "max_new_tokens": N, "temperature": T}
                   -> {"tokens": [...], "rid": ..., "latency_s": ...}
  GET  /metrics    Prometheus text incl. tpu_serving_queue_depth — the HPA
                   signal (scale on queue depth, BASELINE.json config 5)
  GET  /healthz    liveness

Run: python -m k8s_runpod_kubelet_tpu.workloads.serve_main \
        --model gemma-7b --slots 8 --port 8000
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

log = logging.getLogger("serve-main")


class _Handler(BaseHTTPRequestHandler):
    engine = None  # bound below
    request_timeout_s = 120.0

    def log_message(self, *a):
        pass

    def _send(self, status: int, payload: dict | bytes,
              ctype: str = "application/json"):
        body = payload if isinstance(payload, bytes) else json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/healthz":
            if not self.engine.alive:
                return self._send(503, b"engine thread dead", "text/plain")
            return self._send(200, b"ok", "text/plain")
        if self.path == "/metrics":
            return self._send(200, self.engine.metrics.render().encode(),
                              "text/plain; version=0.0.4")
        self._send(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        if self.path != "/generate":
            return self._send(404, {"error": f"no route {self.path}"})
        try:
            length = int(self.headers.get("Content-Length") or 0)
            req = json.loads(self.rfile.read(length)) if length else {}
            tokens = req["tokens"]
            if not isinstance(tokens, list) or not all(
                    isinstance(t, int) for t in tokens):
                raise ValueError("tokens must be a list of ints")
        except (json.JSONDecodeError, KeyError, ValueError, TypeError) as e:
            return self._send(400, {"error": f"bad request: {e}"})
        fut = self.engine.submit(tokens, req.get("max_new_tokens"),
                                 req.get("temperature"))
        try:
            out = fut.result(timeout=self.request_timeout_s)
        except FutureTimeout:
            return self._send(504, {"error": "generation timed out"})
        except ValueError as e:
            return self._send(400, {"error": str(e)})
        self._send(200, out)


def serve(engine, port: int = 8000, request_timeout_s: float = 120.0):
    handler = type("BoundHandler", (_Handler,),
                   {"engine": engine, "request_timeout_s": request_timeout_s})
    httpd = ThreadingHTTPServer(("0.0.0.0", port), handler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return httpd


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gemma-7b",
                   choices=["gemma-7b", "llama3-8b", "mixtral-8x7b",
                            "tiny", "tiny-moe"])
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--cache-len", type=int, default=2048)
    p.add_argument("--max-new-tokens", type=int, default=256)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    import jax
    from ..models import gemma_7b, llama3_8b, mixtral_8x7b, tiny_llama, tiny_moe, init_params
    from .serving import ServingConfig, ServingEngine

    cfg = {"gemma-7b": gemma_7b, "llama3-8b": llama3_8b,
           "mixtral-8x7b": mixtral_8x7b, "tiny": tiny_llama,
           "tiny-moe": tiny_moe}[args.model]()
    log.info("loading %s (%.2fB params) on %s", cfg.name,
             cfg.param_count / 1e9, jax.default_backend())
    params = init_params(cfg, jax.random.PRNGKey(0))
    engine = ServingEngine(cfg, params, ServingConfig(
        slots=args.slots, cache_len=args.cache_len,
        max_new_tokens=args.max_new_tokens,
        max_prefill_len=args.cache_len // 2)).start()
    httpd = serve(engine, args.port)
    log.info("serving on :%d (POST /generate, GET /metrics)", args.port)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    httpd.shutdown()
    engine.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
