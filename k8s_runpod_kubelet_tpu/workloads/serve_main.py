"""Serving workload: HTTP front end over the ServingEngine (config 5).

The pod command for autoscaled inference. Endpoints:
  POST /generate   {"tokens": [...], "max_new_tokens": N, "temperature": T,
                    "top_k": K, "top_p": P, "stop": [[...], ...]}
                   or {"text": "..."} when --tokenizer is set (the response
                   then also carries decoded "text"; "stop" may then be
                   strings)
                   -> {"tokens": [...], "rid": ..., "latency_s": ...}
                   with "stream": true -> chunked NDJSON: one {"token": N}
                   line per decoded token, then the final result object
                   (JetStream-style streamed decode)
  POST /v1/completions  OpenAI-compatible completions (prompt/max_tokens/
                   temperature/top_p/stop/logprobs/seed/n/presence_penalty/
                   frequency_penalty/logit_bias/stream-SSE), so
                   OpenAI-SDK clients point here unchanged; "model" selects
                   a registered LoRA adapter (vLLM convention); client
                   timeouts cancel the engine-side generation
  POST /v1/chat/completions  OpenAI chat (messages through the model's own
                   HF chat template when present), stream or not
  POST /v1/embeddings  OpenAI embeddings: mean-pooled final-norm hidden
                   states (string/tokens/lists input)
  POST /prefix     register a shared prompt prefix (system prompt): its KV
                   prefills once; prompts starting with it skip it
  POST /adapters   {"name": ..., "path": adapter.npz} — register a trained
                   LoRA adapter (train_main --export-adapter) live
  GET  /metrics    Prometheus text incl. tpu_serving_queue_depth — the HPA
                   signal (scale on queue depth, BASELINE.json config 5) —
                   plus the SLO histograms (tpu_serving_ttft_seconds,
                   tpu_serving_inter_token_seconds, queue-wait, batch
                   utilization, KV-cache occupancy)
  GET  /healthz    liveness (200 while the engine thread lives, even
                   draining); GET /readyz is the ROUTABILITY probe (503
                   while draining) — see do_GET for the full contract
  POST /kv_prefill disaggregated prefill hop (router -> prefill replica):
                   tokenize the forwarded request, prefill its KV through
                   the prefix-cache path, and push the page run to the
                   decode replica named by "handoff_to". When the router
                   annotates "device": true (both replicas advertise the
                   same placement domain) the run moves DEVICE-NATIVE —
                   arena-to-arena buffers, zero numpy/HTTP bytes — and
                   downgrades to the wire codec on any failure; with
                   chunked prefill on (--serving-chunk-tokens) either
                   path STREAMS sequence-numbered chunk frames/fragments
                   while the next chunk is still computing
                   (compute/transfer overlap)
  POST /kv_adopt   decode-side adoption: a pushed KV page run lands in
                   this engine's arena via the prefix trie, so the
                   upcoming request references it zero-copy
  POST /kv_adopt_chunk  streamed adoption: one chunk frame in, buffered
                   strictly in order; the arena moves only when the final
                   frame closes a fully-valid stream (all-or-nothing)
  POST /kv_adopt_shm  cross-process push adoption (ISSUE 16): mmap a
                   sender-parked tmpfs blob (path-validated) and adopt it
                   through the wire codec's validators; the sender unlinks
  POST /kv_pull    owner side of a directory pull: export an
                   already-computed page run match-only (404 {"gone"} when
                   the arena evicted it) as a response blob, or as a
                   tmpfs path for same-host pullers ("via": "shm")
  POST /kv_fetch   cold-replica side of a directory pull: fetch a
                   directory-matched prefix from its owner over the
                   fastest reachable rung (device → shm → wire) and adopt
                   it; always HTTP 200 — a failed pull just re-prefills
  POST /drain      graceful drain (fleet scale-down): stop admitting,
                   finish in-flight, then the fleet reporter deregisters
  GET  /debug/traces  recent request span trees as JSON (?trace_id= filters
                   to the trace a traceparent header named); the generation
                   routes parse inbound W3C ``traceparent`` headers and
                   stamp one into the response so callers can correlate
  GET  /debug/engine  statusz snapshot: per-slot request age/tokens, queue
                   depth, prefix/adapter occupancy

Run: python -m k8s_runpod_kubelet_tpu.workloads.serve_main \
        --model gemma-7b --slots 8 --port 8000
"""

from __future__ import annotations

import argparse
import itertools
import json
import logging
import os
import threading
import time
import urllib.parse
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..tracing import Tracer, format_traceparent, parse_traceparent

log = logging.getLogger("serve-main")

# request-id uniqueness tail (see _openai_completion: the wall stamp alone
# repeats under an injected test clock)
_RID_SEQ = itertools.count()


def _or(value, default):
    """JSON null falls back to the default, matching absent-key handling
    (clients serialize unset option structs as nulls)."""
    return default if value is None else value


class _Handler(BaseHTTPRequestHandler):
    engine = None  # bound below
    tokenizer = None  # bound below; None = token-ids-only API
    request_timeout_s = 120.0
    allow_adapters = False  # POST /adapters opt-in (--dynamic-adapters)
    # streamed handoff (ISSUE 10): max chunk fragments queued between the
    # engine's chunked prefill and the sender thread pushing them to the
    # decode replica — the compute/transfer overlap window. Engine compute
    # BLOCKS when the window is full (bounds host memory; transfer is the
    # bottleneck then anyway).
    handoff_stream_window = 8
    # device-native KV transfer (ISSUE 11): this replica's placement
    # domain ("" = device path off — every hop rides the wire codec).
    # When the router annotates a hop with device:true, /kv_prefill tries
    # the arena-to-arena path first and DOWNGRADES to wire on any failure
    # (bus miss, domain mismatch, geometry, failed adoption).
    device_domain = ""
    # KV-fabric pull (ISSUE 16): budget for one hop of a directory pull
    # (owner export + transfer + adoption)
    pull_timeout_s = 10.0
    # owner-side GC for shm pull blobs a dead puller never unlinked
    # (fleet/device_transfer.ShmBlobGC, bound in serve() with the domain)
    shm_gc = None
    # clock seams, rebound by serve(clock=..., mono=...): wall time for
    # OpenAI `created` stamps / request ids, monotonic for deadlines —
    # injected so stress/soak tests drive HTTP-layer timeouts deterministically
    clock = staticmethod(time.time)
    mono = staticmethod(time.monotonic)
    # GET /debug/profile gate (ISSUE 17): capture stalls the device and
    # writes local files, so it stays 403 unless the operator opted in;
    # sleep is seamed like the clocks so tests capture without real waits
    profile_capture = False
    sleep = staticmethod(time.sleep)
    # chunked transfer framing is an HTTP/1.1 construct; 1.0 clients would
    # read raw chunk framing as the body (non-stream responses all send
    # Content-Length, so keep-alive stays correct)
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _send(self, status: int, payload: dict | bytes,
              ctype: str = "application/json",
              extra_headers: dict | None = None):
        body = payload if isinstance(payload, bytes) else json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        if self.close_connection:  # tell the client, don't just hang up
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(body)

    def _trace_ctx(self) -> tuple[dict, dict]:
        """(submit kwargs, response headers) for this request's trace: an
        inbound W3C ``traceparent`` donates the trace_id + parent span (so
        the caller's tracing system owns the trace); otherwise a fresh
        trace_id is minted. The request's ROOT span id is minted here —
        before the request runs — so every response (stream or not) can
        stamp a traceparent the caller can feed to /debug/traces."""
        inbound = parse_traceparent(self.headers.get("traceparent"))
        trace_id = inbound[0] if inbound else Tracer.new_trace_id()
        parent = inbound[1] if inbound else ""
        root = Tracer.new_span_id()
        return ({"trace_id": trace_id, "parent_span": parent,
                 "span_id": root},
                {"traceparent": format_traceparent(trace_id, root)})

    def _tenant_kw(self, body: dict) -> dict:
        """Cost-attribution tenant (ISSUE 20 — the ROADMAP item-4
        accounting seam): the optional ``X-Tenant`` header wins (the fleet
        router forwards it verbatim); the OpenAI ``user`` field is the
        SDK-compatible fallback. Length-bounded — the string becomes a
        cost-ledger key, and the ledger caps tenant cardinality."""
        tenant = self.headers.get("X-Tenant") or body.get("user") or ""
        return {"tenant": str(tenant)[:64]}

    def _overloaded(self, e, openai: bool = False):
        """429 + Retry-After for an EngineOverloaded admission rejection —
        the bounded-latency contract's client-visible half. An
        EngineDraining rejection rides the same shape at 503 (retryable
        against ANOTHER replica — the fleet router already stopped
        routing here, this answers clients that connected directly)."""
        from .serving import EngineDraining
        status = 503 if isinstance(e, EngineDraining) else 429
        err = ({"error": {"message": str(e), "type": "overloaded_error"}}
               if openai else {"error": str(e)})
        return self._send(status, err, extra_headers={"Retry-After": "1"})

    def do_GET(self):
        if self.path in ("/healthz", "/metrics"):
            # one response per connection on the observability routes: a
            # connection admitted through the overload RESERVE by peeking
            # "GET /healthz" must not keep-alive its way into POST
            # /generate on the reserved slot (the reserve sheds engine
            # work by contract); scrapes reconnect cheaply
            self.close_connection = True
        if self.path == "/healthz":
            # STATUS CONTRACT (drain and health must not fight):
            #   /healthz = LIVENESS (kubelet restarts on 503): 200 while
            #     the engine thread lives — a draining engine is healthy
            #     (body says "draining" for humans), killing it would drop
            #     its in-flight requests; 503 only when the thread died.
            #   /readyz = ROUTABILITY (the fleet router's probe): 503
            #     while draining or dead, 200 only when admitting.
            if not self.engine.alive:
                return self._send(503, b"engine thread dead", "text/plain")
            if getattr(self.engine, "draining", False):
                return self._send(200, b"draining", "text/plain")
            return self._send(200, b"ok", "text/plain")
        if self.path == "/readyz":
            self.close_connection = True
            if not self.engine.alive:
                return self._send(503, b"engine thread dead", "text/plain")
            if getattr(self.engine, "draining", False):
                return self._send(503, b"draining", "text/plain")
            return self._send(200, b"ready", "text/plain")
        if self.path == "/v1/models":
            # OpenAI model listing: the base model plus registered adapters
            now = int(self.clock())
            data = [{"id": self.engine.cfg.name, "object": "model",
                     "created": now, "owned_by": "base"}]
            data += [{"id": n, "object": "model", "created": now,
                      "owned_by": "adapter"}
                     for n in self.engine.adapter_names]
            return self._send(200, {"object": "list", "data": data})
        if self.path == "/metrics":
            return self._send(200, self.engine.metrics.render().encode(),
                              "text/plain; version=0.0.4")
        url = urllib.parse.urlparse(self.path)
        if url.path == "/debug/traces":
            q = urllib.parse.parse_qs(url.query)
            return self._send(200, self.engine.tracer.query(
                (q.get("trace_id") or [""])[0]))
        if url.path == "/debug/engine":
            return self._send(200, self.engine.debug_snapshot())
        if url.path == "/debug/costs":
            # replica cost ledger (ISSUE 20): cumulative per-tenant
            # chip-seconds/dollars; the same snapshot rides the fleet
            # heartbeat into the router's fleet-wide /debug/costs
            if self.engine.costmeter is None:
                return self._send(404, {"error": "cost meter disabled "
                                                 "(--cost-meter off)"})
            return self._send(200, self.engine.costmeter.snapshot())
        if url.path == "/debug/steps":
            # flight-recorder tail + rollup (ISSUE 17): newest-n step
            # records (oldest first) plus phase/occupancy medians and the
            # per-fn recompile table
            q = urllib.parse.parse_qs(url.query)
            try:
                n = int((q.get("n") or ["64"])[0])
            except ValueError:
                return self._send(400, {"error": "n must be an integer"})
            return self._send(200, self.engine.debug_steps(n))
        if url.path == "/debug/profile":
            # on-demand jax.profiler capture, OFF by default: a trace
            # capture stalls the device and writes to the replica's disk,
            # so an unauthenticated GET must not be able to trigger it
            # unless the operator opted in (--profile-capture /
            # TPU_SERVING_PROFILE_CAPTURE)
            if not self.profile_capture:
                return self._send(
                    403, {"error": "profile capture disabled; start with "
                                   "--profile-capture to enable"})
            q = urllib.parse.parse_qs(url.query)
            try:
                seconds = float((q.get("seconds") or ["1"])[0])
            except ValueError:
                return self._send(400, {"error": "seconds must be a number"})
            if not 0 < seconds <= 30:
                return self._send(
                    400, {"error": "seconds must be in (0, 30]"})
            import tempfile
            import jax
            out_dir = tempfile.mkdtemp(prefix="tpu-serving-profile-")
            with jax.profiler.trace(out_dir):
                self.sleep(seconds)
            return self._send(200, {"profile_dir": out_dir,
                                    "seconds": seconds})
        self._send(404, {"error": f"no route {self.path}"})

    def _read_json(self) -> dict:
        """One body-parsing idiom for every POST route."""
        length = int(self.headers.get("Content-Length") or 0)
        return json.loads(self.rfile.read(length)) if length else {}

    def _parse_stop(self, raw) -> tuple[list, list]:
        """OpenAI-style ``stop``: a string, list of strings (needs the
        tokenizer), or list of token lists. Returns (token sequences,
        stop strings) — tokens encoded WITHOUT special tokens (a
        BOS-prefixed sequence could never match a generated tail).

        Byte-level tokenizers match token-level only (already text-exact:
        one tokenization per string). BPE vocabularies additionally match
        the DECODED text in the engine, so a stop string straddling a
        token boundary still stops generation (the token path stays as a
        cheap fast path for whole-token delimiters)."""
        if raw is None:
            return [], []
        if isinstance(raw, str):
            raw = [raw]
        toks_out, strs_out = [], []
        for s in raw:
            if isinstance(s, str):
                if self.tokenizer is None:
                    raise ValueError("string stop sequences need --tokenizer")
                toks = self.tokenizer.encode_plain(s)
                if toks:
                    toks_out.append(toks)
                if s and not getattr(self.tokenizer, "byte_exact", False):
                    strs_out.append(s)
            elif isinstance(s, list):
                toks_out.append(s)
            else:
                raise ValueError("stop must be string(s) or token lists")
        return toks_out, strs_out

    def _cut_at_stop(self, text: str, stop_strs: list) -> tuple[str, bool]:
        """Truncate at the first occurrence of any stop string (OpenAI
        semantics: stop text never reaches the client)."""
        idxs = [text.find(s) for s in stop_strs]
        idxs = [i for i in idxs if i >= 0]
        if idxs:
            return text[:min(idxs)], True
        return text, False

    def _request_tokens(self, path: str, body: dict) -> list:
        """Tokenize a request body into prompt token ids — the ONE
        tokenization path shared by the live routes (/generate, /prefix,
        /v1/completions, /v1/chat/completions) and the /kv_prefill
        handoff hop. Sharing is load-bearing: the prefill replica must
        produce the token ids the decode replica's prompt will match, or
        the handed-off pages never hit — a divergent copy would be a
        silent perf regression, not an error."""
        if not isinstance(body, dict):
            raise ValueError("request must be an object")
        if path == "/v1/chat/completions":
            messages = body.get("messages")
            if not (isinstance(messages, list) and messages and all(
                    isinstance(m, dict) and isinstance(m.get("role"), str)
                    and isinstance(m.get("content"), str) for m in messages)):
                raise ValueError("messages must be a non-empty list of "
                                 "{role, content} objects")
            if self.tokenizer is None:
                raise ValueError("chat completions need --tokenizer")
            tokens = list(self.tokenizer.apply_chat(messages))
            if not tokens:
                raise ValueError("empty prompt")
            return tokens
        if path == "/v1/completions":
            prompt = body.get("prompt", "")
            if isinstance(prompt, list) and all(
                    isinstance(t, int) for t in prompt):
                tokens = prompt
            elif isinstance(prompt, str):
                if self.tokenizer is None:
                    raise ValueError("string prompts need --tokenizer; "
                                     "send a token list instead")
                tokens = self.tokenizer.encode(prompt)
            else:
                raise ValueError("prompt must be a string or token list")
            if not tokens:
                raise ValueError("empty prompt")
            return tokens
        # /generate and /prefix share the tokens/text body format
        if "text" in body and "tokens" not in body:
            if self.tokenizer is None:
                raise ValueError(
                    'server has no tokenizer (start with --tokenizer '
                    'bytes or a HF tokenizer dir) — send "tokens"')
            if not isinstance(body["text"], str):
                raise ValueError("text must be a string")
            tokens = self.tokenizer.encode(body["text"])
            if not tokens:
                raise ValueError("text tokenized to nothing")
            return tokens
        tokens = body.get("tokens")
        if not isinstance(tokens, list) or not all(
                isinstance(t, int) for t in tokens):
            raise ValueError("tokens must be a list of ints")
        return tokens

    def _kv_prefill(self):
        """Disaggregated prefill hop (router -> prefill replica): compute
        the prompt's KV through the engine's prefix-cache prefill path
        and PUSH the serialized page run straight to the decode replica's
        /kv_adopt. Runs on this handler thread (a prefill-role replica's
        whole job). The serving.kv_prefill span parents under the
        router's fleet.handoff via the inbound traceparent — one trace_id
        joins both engines' spans."""
        tr = self.engine.tracer
        inbound = parse_traceparent(self.headers.get("traceparent"))
        trace_id = inbound[0] if inbound else Tracer.new_trace_id()
        parent = inbound[1] if inbound else ""
        span_id = Tracer.new_span_id()
        started = tr.clock()

        def span(ok: bool, attrs: dict):
            try:
                tr.record("serving.kv_prefill", started, tr.clock(),
                          trace_id=trace_id, span_id=span_id,
                          parent_id=parent, attrs={"ok": ok, **attrs})
            except Exception:  # noqa: BLE001 — tracing never fails the hop
                log.exception("serving.kv_prefill span failed")

        try:
            req = self._read_json()
            target = req.get("handoff_to")
            if not (isinstance(target, str) and target):
                raise ValueError('need "handoff_to" (decode replica URL)')
        except (json.JSONDecodeError, ValueError, TypeError) as e:
            span(False, {"error": str(e)})
            return self._send(400, {"ok": False, "error": str(e)})
        try:
            tokens = self._request_tokens(
                str(req.get("path") or "/generate"),
                req.get("request") or {})
            # preflight BEFORE any compute: a prompt under one full page
            # has nothing to hand off — running the prefill here would
            # just double it (the fallback replica prefills again)
            if len(tokens) < self.engine.sc.kv_page_tokens:
                raise ValueError(
                    f"prompt of {len(tokens)} tokens is under one "
                    f"{self.engine.sc.kv_page_tokens}-token page")
        except (ValueError, TypeError) as e:
            # expected decline (short prompt, no tokenizer for this
            # route), not a failure: the router falls back quietly and
            # neither side's failure counter moves
            span(False, {"skip": True, "error": str(e)})
            return self._send(200, {"ok": False, "skip": True,
                                    "error": str(e)})
        if req.get("device") and self.device_domain:
            # device-native path (ISSUE 11): the router saw matching
            # placement domains — hand the run arena-to-arena with zero
            # host copies. ANY failure here downgrades to the wire codec
            # below (then the router's unified fallback catches a wire
            # failure too): the ladder is device -> wire -> unified, and
            # a downgrade is an observability event, never a client error.
            from ..fleet.device_transfer import device_push
            try:
                out = device_push(self.engine, target, tokens,
                                  domain=self.device_domain,
                                  window=self.handoff_stream_window,
                                  # the router's view of the hop's shared
                                  # domain: on a bus miss, an equal domain
                                  # means same host — the run can ride the
                                  # cross-process shm rung (ISSUE 16)
                                  target_domain=str(
                                      req.get("device_domain") or ""),
                                  timeout_s=self.request_timeout_s)
            except Exception as e:  # noqa: BLE001 — every device failure
                # downgrades; the wire path below is the handler
                self.engine.metrics.incr(
                    "tpu_serving_kv_handoff_device_downgrades")
                log.warning("device handoff to %s downgraded to wire: %s",
                            target, e)
            else:
                span(True, {"path": out.get("path", "device"),
                            "tokens": len(tokens),
                            "pages": out["pages"], "bytes": out["bytes"],
                            "streamed": out["streamed"],
                            "chunks": out.get("chunks"),
                            "matched_tokens": out["matched_tokens"]})
                return self._send(200, {"ok": True, **out})
        if self.engine.sc.serving_chunk_tokens > 0:
            # ISSUE 10: chunked engines STREAM the handoff — each
            # completed chunk's page run pushes to the decode replica
            # while the next chunk computes (frames to /kv_adopt_chunk),
            # overlapping compute with transfer
            return self._kv_prefill_streamed(tokens, target, trace_id,
                                             span_id, span)
        try:
            out = self.engine.export_handoff(tokens)
        except Exception as e:  # noqa: BLE001 — export counts its own failures
            span(False, {"tokens": len(tokens), "error": str(e)})
            return self._send(502, {"ok": False, "error": str(e)})
        blob = out["blob"]
        try:
            import urllib.request
            push = urllib.request.Request(
                target.rstrip("/") + "/kv_adopt", data=blob,
                headers={"Content-Type": "application/octet-stream",
                         "traceparent": format_traceparent(trace_id,
                                                           span_id)},
                method="POST")
            with urllib.request.urlopen(
                    push, timeout=self.request_timeout_s) as resp:
                adopted = json.loads(resp.read() or b"{}")
            if not adopted.get("ok"):
                raise OSError(f"decode replica refused adoption: {adopted}")
        except Exception as e:  # noqa: BLE001 — any push failure = failed hop
            self.engine.metrics.incr("tpu_serving_kv_handoff_failures")
            span(False, {"tokens": len(tokens), "pages": out["pages"],
                         "error": str(e)})
            return self._send(502, {"ok": False, "error": str(e)})
        span(True, {"path": "wire", "tokens": len(tokens),
                    "pages": out["pages"], "bytes": len(blob),
                    "matched_tokens": out["matched_tokens"]})
        return self._send(200, {
            "ok": True, "path": "wire", "pages": out["pages"],
            "bytes": len(blob),
            "covered_tokens": out["covered_tokens"],
            "matched_tokens": out["matched_tokens"],
            "adopted": adopted.get("pages")})

    def _kv_prefill_streamed(self, tokens: list, target: str,
                             trace_id: str, span_id: str, span):
        """The chunked/overlapped prefill hop: the engine's
        export_handoff_stream computes chunk by chunk and hands each
        completed page run to a SENDER THREAD here, which serializes the
        frame and POSTs it to the decode replica's /kv_adopt_chunk while
        the next chunk is still computing. The queue between them is the
        handoff_stream_window — compute blocks when transfer falls that
        far behind. Per-chunk serving.kv_chunk (compute) and
        serving.kv_push (serialize + POST) spans parent under this hop's
        serving.kv_prefill, so the chunk timeline renders per trace
        (tools/fleet_summary.py). Any frame failure aborts the stream:
        502 to the router, which falls back — the decode side's partial
        stream buffer expires without ever touching its arena."""
        import queue as _q
        import uuid

        import numpy as np

        from ..fleet.handoff import (serialize_chunk_frame,
                                     serialize_pages)
        tr = self.engine.tracer
        stream_id = uuid.uuid4().hex
        page_tokens = self.engine.sc.kv_page_tokens
        sendq: "_q.Queue" = _q.Queue(
            maxsize=max(1, int(self.handoff_stream_window)))
        push_err: list = []
        stats = {"frames": 0, "bytes": 0, "push_s": 0.0}

        def chunk_span(t0, attrs):
            try:
                tr.record("serving.kv_chunk", t0, tr.clock(),
                          trace_id=trace_id, parent_id=span_id, attrs=attrs)
            except Exception:  # noqa: BLE001 — tracing never fails the hop
                log.exception("serving.kv_chunk span failed")

        def push_span(t0, attrs):
            try:
                tr.record("serving.kv_push", t0, tr.clock(),
                          trace_id=trace_id, parent_id=span_id, attrs=attrs)
            except Exception:  # noqa: BLE001 — tracing never fails the hop
                log.exception("serving.kv_push span failed")

        def sender():
            # ONE keep-alive connection for the whole stream: a fresh TCP
            # (and in real fleets TLS/proxy) handshake per frame would
            # serialize setup RTTs into the push leg — the very wire time
            # the stream exists to hide. Any failure aborts the hop, so
            # there is no reconnect path to maintain.
            import http.client
            parsed = urllib.parse.urlsplit(target)
            path = parsed.path.rstrip("/") + "/kv_adopt_chunk"
            conn = None
            try:
                while True:
                    frag = sendq.get()
                    if frag is None:
                        return
                    t0w, t0 = tr.clock(), self.mono()
                    try:
                        payload = b""
                        if frag["sections"]:
                            # host copy + pow2-padding trim happen HERE,
                            # on the sender thread — never on the compute
                            # thread (the export_handoff_stream fragment
                            # contract)
                            n = len(frag["tokens"]) // page_tokens
                            sections = {
                                name: np.asarray(a)[:, :n]
                                for name, a in frag["sections"].items()}
                            payload = serialize_pages(
                                frag["tokens"], page_tokens, sections,
                                model=self.engine.cfg.name)
                        blob = serialize_chunk_frame(
                            stream_id, frag["seq"], payload,
                            final=frag["final"],
                            total_tokens=frag.get("total_tokens"))
                        if conn is None:
                            import socket as _socket
                            if parsed.scheme == "https":
                                # a TLS-fronted decode replica must work
                                # on the streamed path exactly like the
                                # monolithic urllib push does
                                conn = http.client.HTTPSConnection(
                                    parsed.hostname, parsed.port or 443,
                                    timeout=self.request_timeout_s)
                            else:
                                conn = http.client.HTTPConnection(
                                    parsed.hostname, parsed.port or 80,
                                    timeout=self.request_timeout_s)
                            conn.connect()
                            # headers and body go out as separate writes
                            # (write-write-read): on a keep-alive
                            # connection Nagle + delayed ACK turn that
                            # into ~40ms per frame — disable Nagle
                            conn.sock.setsockopt(_socket.IPPROTO_TCP,
                                                 _socket.TCP_NODELAY, 1)
                        conn.request(
                            "POST", path, body=blob,
                            headers={"Content-Type":
                                     "application/octet-stream",
                                     "traceparent": format_traceparent(
                                         trace_id, span_id)})
                        resp = conn.getresponse()
                        reply = json.loads(resp.read() or b"{}")
                        if resp.status != 200 or not reply.get("ok"):
                            raise OSError(f"decode replica refused frame "
                                          f"{frag['seq']}: {resp.status} "
                                          f"{reply}")
                        stats["frames"] += 1
                        stats["bytes"] += len(blob)
                        stats["push_s"] += self.mono() - t0
                        self.engine.metrics.incr(
                            "tpu_serving_kv_handoff_bytes", len(blob))
                        push_span(t0w,
                                  {"seq": frag["seq"],
                                   "final": frag["final"],
                                   "bytes": len(blob),
                                   "pages": len(frag["tokens"])
                                   // page_tokens})
                    except Exception as e:  # noqa: BLE001 — any failure
                        # = failed hop; emit sees push_err and aborts the
                        # export, finish_sender lands the sentinel
                        push_err.append(e)
                        push_span(t0w, {"seq": frag["seq"], "ok": False,
                                        "error": str(e)})
                        return
            finally:
                if conn is not None:
                    conn.close()

        chunk_t0 = [tr.clock()]

        def emit(frag):
            chunk_span(chunk_t0[0],
                       {"seq": frag["seq"], "final": frag["final"],
                        "tokens": len(frag["tokens"]),
                        "pages": len(frag["tokens"]) // page_tokens})
            chunk_t0[0] = tr.clock()
            while True:
                if push_err:
                    raise OSError(f"stream push failed: {push_err[0]}")
                try:
                    sendq.put(frag, timeout=0.1)
                    return
                except _q.Full:
                    continue

        thread = threading.Thread(target=sender, name="kv-handoff-sender",
                                  daemon=True)

        def finish_sender(abort: bool):
            """Land the close sentinel UNCONDITIONALLY — a dropped
            sentinel would strand the sender in get() forever and leak a
            thread per failed hop. On abort, pending frames are stale:
            drain them (the handler is the only producer and it has
            stopped, so capacity for the sentinel is then guaranteed).
            On success the sender must still push everything queued, so
            wait for slots — falling back to the drain only if the
            sender dies mid-flush."""
            if not abort:
                while not push_err:
                    try:
                        sendq.put(None, timeout=0.1)
                        thread.join(timeout=self.request_timeout_s)
                        return
                    except _q.Full:
                        continue
            while True:
                try:
                    sendq.get_nowait()
                except _q.Empty:
                    break
            sendq.put(None)
            thread.join(timeout=self.request_timeout_s)

        t_start = self.mono()
        thread.start()
        try:
            out = self.engine.export_handoff_stream(tokens, emit)
            compute_s = self.mono() - t_start
        except Exception as e:  # noqa: BLE001 — export counts its failures
            span(False, {"streamed": True, "tokens": len(tokens),
                         "error": str(e)})
            finish_sender(abort=True)
            return self._send(502, {"ok": False, "error": str(e)})
        finish_sender(abort=False)
        wall_s = self.mono() - t_start
        if thread.is_alive():
            # the transfer outlived the request budget: the final frame's
            # adoption is UNCONFIRMED — reporting ok here would record a
            # successful handoff (and racy stats) while the decode side
            # may never adopt. Fail the hop; the router falls back. The
            # daemon sender drains to its sentinel and exits on its own.
            push_err.append(OSError(
                f"transfer outlived request_timeout_s="
                f"{self.request_timeout_s}; adoption unconfirmed"))
        if push_err:
            self.engine.metrics.incr("tpu_serving_kv_handoff_failures")
            span(False, {"streamed": True, "tokens": len(tokens),
                         "chunks": out["chunks"],
                         "error": str(push_err[0])})
            return self._send(502, {"ok": False,
                                    "error": str(push_err[0])})
        # realized overlap: how much of the smaller leg (compute or
        # transfer) actually hid behind the other — the "serial vs
        # streamed" efficiency the bench sweep records
        floor = min(compute_s, stats["push_s"])
        overlap = max(0.0, compute_s + stats["push_s"] - wall_s)
        overlap_ratio = round(min(1.0, overlap / floor), 3) if floor > 1e-9 \
            else 0.0
        span(True, {"path": "wire", "streamed": True,
                    "tokens": len(tokens),
                    "pages": out["pages"], "chunks": out["chunks"],
                    "bytes": stats["bytes"],
                    "matched_tokens": out["matched_tokens"],
                    "overlap_ratio": overlap_ratio})
        return self._send(200, {
            "ok": True, "path": "wire", "streamed": True,
            "pages": out["pages"],
            "bytes": stats["bytes"], "chunks": out["chunks"],
            "covered_tokens": out["covered_tokens"],
            "matched_tokens": out["matched_tokens"],
            "overlap_ratio": overlap_ratio,
            "compute_s": round(compute_s, 6),
            "push_s": round(stats["push_s"], 6),
            "wall_s": round(wall_s, 6)})

    def _kv_adopt_chunk(self):
        """Decode-side half of a STREAMED handoff: one chunk frame in,
        buffered in strict order; the arena moves only when the final
        frame closes a fully-valid stream (engine.adopt_handoff_chunk —
        all-or-nothing). 400 on any rejection: the sender aborts the
        stream and the router falls back."""
        tr = self.engine.tracer
        inbound = parse_traceparent(self.headers.get("traceparent"))
        trace_id = inbound[0] if inbound else Tracer.new_trace_id()
        parent = inbound[1] if inbound else ""
        started = tr.clock()
        length = int(self.headers.get("Content-Length") or 0)
        blob = self.rfile.read(length) if length else b""

        def span(ok: bool, attrs: dict):
            try:
                tr.record("serving.kv_adopt_chunk", started, tr.clock(),
                          trace_id=trace_id, parent_id=parent,
                          attrs={"ok": ok, **attrs})
            except Exception:  # noqa: BLE001 — tracing never fails the hop
                log.exception("serving.kv_adopt_chunk span failed")

        try:
            out = self.engine.adopt_handoff_chunk(blob)
        except Exception as e:  # noqa: BLE001 — engine counts its failures
            span(False, {"bytes": len(blob), "error": str(e)})
            return self._send(400, {"ok": False, "error": str(e)})
        span(True, {"bytes": len(blob), "seq": out.get("seq"),
                    "final": out["final"],
                    **({"pages": out["pages"]} if out["final"] else {})})
        return self._send(200, out)

    def _kv_adopt(self):
        """Decode-side half: adopt a pushed KV page run into this
        engine's arena (prefix trie) so the upcoming request's prompt
        match references it zero-copy."""
        tr = self.engine.tracer
        inbound = parse_traceparent(self.headers.get("traceparent"))
        trace_id = inbound[0] if inbound else Tracer.new_trace_id()
        parent = inbound[1] if inbound else ""
        started = tr.clock()
        length = int(self.headers.get("Content-Length") or 0)
        blob = self.rfile.read(length) if length else b""

        def span(ok: bool, attrs: dict):
            try:
                tr.record("serving.kv_adopt", started, tr.clock(),
                          trace_id=trace_id, parent_id=parent,
                          attrs={"ok": ok, **attrs})
            except Exception:  # noqa: BLE001 — tracing never fails the hop
                log.exception("serving.kv_adopt span failed")

        try:
            out = self.engine.adopt_handoff(blob)
        except Exception as e:  # noqa: BLE001 — adopt counts its own failures
            span(False, {"bytes": len(blob), "error": str(e)})
            return self._send(400, {"ok": False, "error": str(e)})
        span(True, out)
        return self._send(200, {"ok": True, **out})

    def _kv_adopt_shm(self):
        """Receiver half of the cross-process PUSH rung (ISSUE 16): the
        sender parked a handoff blob in the shm dir and POSTs only its
        PATH; mmap it and adopt through the same deserialize_pages
        validation the wire door runs (the codec slices an mmap like
        bytes — zero socket payload, zero extra copies). The SENDER owns
        the file's lifecycle (it unlinks in a finally whether or not
        this adoption lands), so this door only closes its mapping. 400
        on any refusal: the sender downgrades to wire."""
        tr = self.engine.tracer
        inbound = parse_traceparent(self.headers.get("traceparent"))
        trace_id = inbound[0] if inbound else Tracer.new_trace_id()
        parent = inbound[1] if inbound else ""
        started = tr.clock()

        def span(ok: bool, attrs: dict):
            try:
                tr.record("serving.kv_adopt", started, tr.clock(),
                          trace_id=trace_id, parent_id=parent,
                          attrs={"ok": ok, "path": "shm", **attrs})
            except Exception:  # noqa: BLE001 — tracing never fails the hop
                log.exception("serving.kv_adopt span failed")

        from ..fleet.device_transfer import open_shm_blob
        try:
            req = self._read_json()
            blob = open_shm_blob(str(req.get("path") or ""))
        except Exception as e:  # noqa: BLE001 — a vanished/foreign/torn
            # path is the sender's downgrade signal, never a crash here
            span(False, {"error": str(e)})
            return self._send(400, {"ok": False, "error": str(e)})
        try:
            out = self.engine.adopt_handoff(blob)
        except Exception as e:  # noqa: BLE001 — adopt counts its own failures
            span(False, {"bytes": len(blob), "error": str(e)})
            return self._send(400, {"ok": False, "error": str(e)})
        finally:
            blob.close()
        span(True, out)
        return self._send(200, {"ok": True, **out})

    def _kv_pull(self):
        """OWNER side of a directory pull (ISSUE 16): a cold replica's
        /kv_fetch asks this engine for an already-computed page run.
        export_pull is MATCH-ONLY — it never prefills — so a run the
        arena evicted answers 404 {"gone": true}: the puller reports
        GONE, the router invalidates the directory entry, and the
        request re-prefills (every pull rung reads this same trie —
        walking the ladder after a miss would be a retry storm against
        pages that no longer exist). ``via: "shm"`` parks the blob in
        tmpfs and replies with its path (a same-host puller mmaps it and
        unlinks after adoption; ShmBlobGC sweeps what dead pullers
        leave); the default answers the blob in the response body
        (wire)."""
        tr = self.engine.tracer
        inbound = parse_traceparent(self.headers.get("traceparent"))
        trace_id = inbound[0] if inbound else Tracer.new_trace_id()
        parent = inbound[1] if inbound else ""
        started = tr.clock()

        def span(ok: bool, attrs: dict):
            try:
                tr.record("serving.kv_pull", started, tr.clock(),
                          trace_id=trace_id, parent_id=parent,
                          attrs={"ok": ok, "side": "owner", **attrs})
            except Exception:  # noqa: BLE001 — tracing never fails the hop
                log.exception("serving.kv_pull span failed")

        from ..fleet.handoff import KVPullMiss
        try:
            req = self._read_json()
            tokens = req.get("tokens")
            if not (isinstance(tokens, list)
                    and all(isinstance(t, int) for t in tokens)):
                raise ValueError("tokens must be a list of ints")
            adapter = str(req.get("adapter") or "")
            via = str(req.get("via") or "wire")
        except (json.JSONDecodeError, ValueError, TypeError) as e:
            span(False, {"error": str(e)})
            return self._send(400, {"ok": False, "error": str(e)})
        try:
            out = self.engine.export_pull(tokens, adapter=adapter)
        except KVPullMiss as e:
            # NOT a failure: the run is gone — directory staleness, which
            # the router's invalidation counter tracks, not this engine's
            span(False, {"gone": True, "error": str(e)})
            return self._send(404, {"ok": False, "gone": True,
                                    "error": str(e)})
        except Exception as e:  # noqa: BLE001 — export counts its failures
            span(False, {"error": str(e)})
            return self._send(502, {"ok": False, "error": str(e)})
        blob = out["blob"]
        if via == "shm":
            from ..fleet.device_transfer import write_shm_blob
            gc = self.shm_gc
            if gc is not None:
                gc.sweep()  # reap blobs a dead puller never unlinked
            try:
                path = write_shm_blob(blob)
            except OSError as e:
                self.engine.metrics.incr("tpu_serving_kv_pull_failures")
                span(False, {"via": "shm", "error": str(e)})
                return self._send(502, {"ok": False, "error": str(e)})
            if gc is not None:
                gc.track(path)
            span(True, {"via": "shm", "pages": out["pages"],
                        "bytes": len(blob)})
            return self._send(200, {
                "ok": True, "path": path, "pages": out["pages"],
                "bytes": len(blob),
                "covered_tokens": out["covered_tokens"]})
        span(True, {"via": "wire", "pages": out["pages"],
                    "bytes": len(blob)})
        return self._send(
            200, blob, "application/octet-stream",
            extra_headers={
                "X-KV-Pages": str(out["pages"]),
                "X-KV-Covered-Tokens": str(out["covered_tokens"])})

    def _owner_pull(self, owner_url: str, payload: dict,
                    trace_id: str, span_id: str):
        """One control POST to the owner's /kv_pull. Returns
        ("gone", msg) when the owner answered that the run no longer
        exists, ("blob", bytes) for a wire-rung body, ("json", dict) for
        a shm-rung path reply; raises OSError on transport-shaped
        failures (the caller walks to the next rung)."""
        import urllib.error
        import urllib.request
        req = urllib.request.Request(
            owner_url.rstrip("/") + "/kv_pull",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": format_traceparent(trace_id, span_id)},
            method="POST")
        try:
            with urllib.request.urlopen(
                    req, timeout=self.pull_timeout_s) as resp:
                ctype = resp.headers.get("Content-Type") or ""
                raw = resp.read()
        except urllib.error.HTTPError as e:
            body = e.read()
            try:
                parsed = json.loads(body or b"{}")
            except json.JSONDecodeError:
                parsed = {}
            if e.code == 404 and parsed.get("gone"):
                return ("gone", str(parsed.get("error") or "gone"))
            raise OSError(f"owner /kv_pull answered {e.code}: "
                          f"{parsed.get('error') or body[:200]!r}") from e
        if "octet-stream" in ctype:
            return ("blob", raw)
        out = json.loads(raw or b"{}")
        if not isinstance(out, dict):
            raise OSError(f"owner /kv_pull answered non-object: {out!r}")
        if out.get("gone"):
            return ("gone", str(out.get("error") or "gone"))
        if not out.get("ok"):
            raise OSError(f"owner /kv_pull refused: {out}")
        return ("json", out)

    def _kv_fetch(self):
        """COLD-REPLICA side of a directory pull (ISSUE 16): the router
        found this request's prompt prefix in the fleet directory under
        ANOTHER replica and asks this engine to fetch the pages before
        the request lands, instead of re-prefilling them. Walks the pull
        ladder fastest-first — device (owner in this process, zero
        copies) → shm (same host, blob through tmpfs) → wire (blob in
        the owner's response body) — with the push ladder's downgrade
        discipline: transport failures walk DOWN a rung, but a
        KVPullMiss at ANY rung answers {"gone": true} immediately (every
        rung reads the owner's one trie; the run is gone at all of them,
        and the router must invalidate the directory entry, not retry).
        Always HTTP 200: a failed pull is a missed optimization — the
        request simply prefills — never an error the client sees."""
        tr = self.engine.tracer
        inbound = parse_traceparent(self.headers.get("traceparent"))
        trace_id = inbound[0] if inbound else Tracer.new_trace_id()
        parent = inbound[1] if inbound else ""
        span_id = Tracer.new_span_id()
        started = tr.clock()

        def span(ok: bool, attrs: dict):
            try:
                tr.record("serving.kv_pull", started, tr.clock(),
                          trace_id=trace_id, span_id=span_id,
                          parent_id=parent,
                          attrs={"ok": ok, "side": "puller", **attrs})
            except Exception:  # noqa: BLE001 — tracing never fails the hop
                log.exception("serving.kv_pull span failed")

        from ..fleet.device_transfer import device_pull, open_shm_blob
        from ..fleet.handoff import KVPullMiss
        try:
            req = self._read_json()
            tokens = req.get("tokens")
            if not (isinstance(tokens, list) and tokens
                    and all(isinstance(t, int) for t in tokens)):
                raise ValueError("tokens must be a non-empty list of ints")
            owner_url = str(req.get("owner_url") or "")
            if not owner_url:
                raise ValueError('need "owner_url"')
            adapter = str(req.get("adapter") or "")
            owner_domain = str(req.get("owner_domain") or "")
            model = str(req.get("model") or "")
        except (json.JSONDecodeError, ValueError, TypeError) as e:
            span(False, {"error": str(e)})
            return self._send(400, {"ok": False, "error": str(e)})
        # preflight the local half of the adoption contract BEFORE any
        # owner traffic: a cross-model entry or an adapter this replica
        # never registered can never adopt — and neither means the
        # OWNER's pages are gone, so answer a plain failure (router
        # proceeds without invalidating)
        if model and model != self.engine.cfg.name:
            msg = (f"directory entry is for model {model!r}, this replica "
                   f"serves {self.engine.cfg.name!r}")
            span(False, {"owner": owner_url, "error": msg})
            return self._send(200, {"ok": False, "error": msg})
        if adapter and adapter not in self.engine.adapter_names:
            msg = f"adapter {adapter!r} is not registered on this replica"
            span(False, {"owner": owner_url, "error": msg})
            return self._send(200, {"ok": False, "error": msg})

        def gone(e):
            span(False, {"gone": True, "owner": owner_url,
                         "error": str(e)})
            return self._send(200, {"ok": False, "gone": True,
                                    "error": str(e)})

        def pulled(pages: int, nbytes: int, covered: int, rung: str):
            self.engine.metrics.incr("tpu_serving_kv_pull_runs")
            self.engine.metrics.incr("tpu_serving_kv_pull_bytes", nbytes)
            span(True, {"path": rung, "owner": owner_url,
                        "pages": pages, "bytes": nbytes,
                        "covered_tokens": covered})
            return self._send(200, {"ok": True, "path": rung,
                                    "pages": pages,
                                    "covered_tokens": covered})

        errors = []
        same_domain = bool(self.device_domain
                           and owner_domain == self.device_domain)
        if same_domain:
            # rung 1: device-local — the owner lives in this very
            # process (bus hit); pages move arena-to-arena
            try:
                out = device_pull(self.engine, owner_url, tokens,
                                  adapter=adapter,
                                  domain=self.device_domain)
                return pulled(out["pages"], out["bytes"],
                              out["covered_tokens"], "device")
            except KVPullMiss as e:
                return gone(e)
            except Exception as e:  # noqa: BLE001 — transport-shaped
                # (bus miss = owner in another process); the shm rung
                # reads the same trie through the codec
                errors.append(f"device: {e}")
            # rung 2: shm — same host, different process: the owner
            # parks the blob in tmpfs, we mmap + adopt + unlink
            try:
                kind, reply = self._owner_pull(
                    owner_url, {"tokens": tokens, "adapter": adapter,
                                "via": "shm"}, trace_id, span_id)
                if kind == "gone":
                    return gone(reply)
                path = str(reply.get("path") or "")
                blob = open_shm_blob(path)
                try:
                    out = self.engine.adopt_handoff(blob, adapter=adapter)
                finally:
                    blob.close()
                    try:
                        os.unlink(path)
                    except OSError:
                        pass  # the owner's GC sweeps it
                return pulled(out["pages"], out["bytes"], out["tokens"],
                              "shm")
            except KVPullMiss as e:
                return gone(e)
            except Exception as e:  # noqa: BLE001 — walk to the wire rung
                errors.append(f"shm: {e}")
        # rung 3: wire — the blob rides the owner's response body
        try:
            kind, reply = self._owner_pull(
                owner_url, {"tokens": tokens, "adapter": adapter},
                trace_id, span_id)
            if kind == "gone":
                return gone(reply)
            if kind != "blob":
                raise OSError(f"owner answered a {kind} reply to a wire "
                              "pull")
            out = self.engine.adopt_handoff(reply, adapter=adapter)
            return pulled(out["pages"], out["bytes"], out["tokens"],
                          "wire")
        except KVPullMiss as e:
            return gone(e)
        except Exception as e:  # noqa: BLE001 — the ladder is exhausted;
            # the request re-prefills (the unified fallback)
            errors.append(f"wire: {e}")
        self.engine.metrics.incr("tpu_serving_kv_pull_failures")
        span(False, {"owner": owner_url, "error": "; ".join(errors)})
        return self._send(200, {"ok": False, "error": "; ".join(errors)})

    def do_POST(self):
        if self.path == "/kv_prefill":
            return self._kv_prefill()
        if self.path == "/kv_adopt":
            return self._kv_adopt()
        if self.path == "/kv_adopt_chunk":
            return self._kv_adopt_chunk()
        if self.path == "/kv_adopt_shm":
            return self._kv_adopt_shm()
        if self.path == "/kv_pull":
            return self._kv_pull()
        if self.path == "/kv_fetch":
            return self._kv_fetch()
        if self.path == "/drain":
            # graceful scale-down (fleet autoscaler contract): stop
            # admitting, finish in-flight. Idempotent; progress is
            # observable via /readyz (503 once draining) and
            # /debug/engine ("drained": true when empty).
            self._read_json()  # drain the body: unread bytes would be
            # parsed as the NEXT request line on this keep-alive connection
            self.engine.drain()
            return self._send(200, {"draining": True,
                                    "queue_depth": self.engine.queue_depth,
                                    "active_slots": self.engine.active_slots})
        if self.path == "/v1/completions":
            return self._openai_completion(chat=False)
        if self.path == "/v1/chat/completions":
            return self._openai_completion(chat=True)
        if self.path == "/v1/embeddings":
            return self._openai_embeddings()
        if self.path == "/adapters":
            # register a LoRA adapter from a save_adapter() .npz so trained
            # adapters go live without a restart (multi-LoRA serving).
            # Opt-in only (--dynamic-adapters): this endpoint makes the
            # server open a caller-chosen filesystem path and hot-swap live
            # tenant weights — vLLM gates its equivalent the same way.
            if not self.allow_adapters:
                return self._send(403, {
                    "error": "dynamic adapter registration is disabled "
                             "(start with --dynamic-adapters)"})
            try:
                req = self._read_json()
                name, path = req.get("name"), req.get("path")
                if not (isinstance(name, str) and name
                        and isinstance(path, str) and path):
                    raise ValueError('need "name" and "path" (adapter .npz)')
                from ..models.lora import load_adapter
                self.engine.register_adapter(name, load_adapter(path))
            except Exception as e:  # noqa: BLE001 — corrupt zips raise
                # BadZipFile/TypeError/..., not just ValueError; an operator
                # endpoint must answer 400, not reset the connection. Log
                # the detail server-side; don't hand path-probing oracles
                # (FileNotFoundError vs BadZipFile) to the client.
                log.warning("adapter registration failed: %s: %s",
                            type(e).__name__, e)
                return self._send(400, {"error": "adapter registration "
                                                 "failed (see server log)"})
            return self._send(200, {"registered": name})
        if self.path not in ("/generate", "/prefix"):
            return self._send(404, {"error": f"no route {self.path}"})
        try:
            req = self._read_json()
            tokens = self._request_tokens(self.path, req)
        except (json.JSONDecodeError, KeyError, ValueError, TypeError) as e:
            return self._send(400, {"error": f"bad request: {e}"})
        if self.path == "/prefix":
            # register a shared prompt prefix (system prompt): its KV is
            # prefilled once and every later prompt starting with it skips
            # straight to the stored cache
            try:
                self.engine.register_prefix(tokens)
            except ValueError as e:
                return self._send(400, {"error": str(e)})
            return self._send(200, {"registered": len(tokens)})
        if req.get("stream"):
            return self._generate_stream(tokens, req)
        try:
            stop, stop_strs = self._parse_stop(req.get("stop"))
        except ValueError as e:
            return self._send(400, {"error": str(e)})
        trace_kw, trace_hdrs = self._trace_ctx()
        fut = self.engine.submit(tokens, req.get("max_new_tokens"),
                                 req.get("temperature"),
                                 top_k=_or(req.get("top_k"), 0),
                                 top_p=_or(req.get("top_p"), 1.0),
                                 presence_penalty=_or(
                                     req.get("presence_penalty"), 0.0),
                                 frequency_penalty=_or(
                                     req.get("frequency_penalty"), 0.0),
                                 logit_bias=req.get("logit_bias"),
                                 stop=stop, stop_text=stop_strs,
                                 logprobs=bool(req.get("logprobs")),
                                 adapter=req.get("adapter") or "",
                                 seed=req.get("seed"), **trace_kw,
                                 **self._tenant_kw(req))
        try:
            out = fut.result(timeout=self.request_timeout_s)
        except FutureTimeout:
            fut.cancel()  # engine frees the slot at its next step
            return self._send(504, {"error": "generation timed out"})
        except ValueError as e:
            return self._send(400, {"error": str(e)})
        except Exception as e:  # engine crash: JSON 500, not a dropped socket
            from .serving import EngineDraining, EngineOverloaded
            if isinstance(e, (EngineOverloaded, EngineDraining)):
                return self._overloaded(e)
            return self._send(500, {"error": str(e)})
        if self.tokenizer is not None:
            out = dict(out)
            text = self.tokenizer.decode(out["tokens"])
            if stop_strs:  # BPE text stop: truncate at its first occurrence
                text, _ = self._cut_at_stop(text, stop_strs)
            out["text"] = text
        self._send(200, out, extra_headers=trace_hdrs)

    def _stream_pump(self, tokens: list, kw: dict, ctype: str, fmt: dict,
                     extra_headers: dict | None = None):
        """Shared streamed-generation pump (NDJSON /generate and SSE
        /v1/completions ride the same concurrency/deadline machinery):
        engine thread pushes tokens into a queue, this handler thread
        drains it to the socket. A broken pipe propagates back into the
        engine's next on_token call, which cancels the request. The
        request_timeout_s deadline bounds the WHOLE request, like the
        non-stream path's fut.result(timeout=...) — not a per-token gap,
        which would let a slow-but-steady stream run unboundedly (ADVICE r1).

        ``fmt`` callbacks each return a list of body bytes to emit:
        token(t), timeout(), error(msg), end(result_dict), and an
        optional start() emitted right after the headers (chat SSE uses
        it for the role-delta chunk, so a generation that ends instantly
        — or times out — still gives strict OpenAI clients a role)."""
        import queue as _q
        q: "_q.Queue" = _q.Queue()
        dead = threading.Event()

        def on_token(t):
            if dead.is_set():  # client gone: raising cancels in the engine
                raise ConnectionError("stream client disconnected")
            q.put(("tok", t))

        fut = self.engine.submit(tokens, on_token=on_token, **kw)
        if fut.done() and fut.exception() is not None:
            from .serving import EngineDraining, EngineOverloaded
            exc = fut.exception()
            if isinstance(exc, (EngineOverloaded, EngineDraining)):
                overloaded = fmt.get("overloaded", fmt["badreq"])
                return self._send(
                    503 if isinstance(exc, EngineDraining) else 429,
                    overloaded(str(exc)),
                    extra_headers={"Retry-After": "1"})
            return self._send(400, fmt["badreq"](str(exc)))
        fut.add_done_callback(lambda f: q.put(("end", f)))
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Transfer-Encoding", "chunked")
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()

        def chunk(body: bytes):
            self.wfile.write(f"{len(body):x}\r\n".encode() + body + b"\r\n")
            self.wfile.flush()

        deadline = self.mono() + self.request_timeout_s
        try:
            for body in fmt.get("start", lambda: [])():
                chunk(body)
            while True:
                try:
                    remaining = deadline - self.mono()
                    if remaining <= 0:
                        raise _q.Empty
                    kind, val = q.get(timeout=remaining)
                except _q.Empty:
                    # deadline passed: tell the client and stop the
                    # engine-side request (the non-stream paths' 504).
                    # cancel() covers a request still QUEUED (on_token never
                    # fires there, so dead alone would never reach it)
                    dead.set()
                    fut.cancel()
                    for body in fmt["timeout"]():
                        chunk(body)
                    break
                if kind == "tok":
                    for body in fmt["token"](val):
                        chunk(body)
                else:
                    exc = val.exception()
                    bodies = (fmt["error"](str(exc)) if exc
                              else fmt["end"](val.result()))
                    for body in bodies:
                        chunk(body)
                    break
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionError, OSError):
            dead.set()  # engine cancels at its next on_token call

    def _openai_embeddings(self):
        """OpenAI /v1/embeddings: mean-pooled final-norm hidden states.
        ``input`` is a string, list of strings, token list, or list of
        token lists (OpenAI accepts all four)."""
        try:
            req = self._read_json()
            model_req = req.get("model")
            if model_req and model_req != self.engine.cfg.name:
                # adapters change only the projection weights the COMPLETION
                # jits apply; the embed forward runs base weights, so
                # silently answering for an adapter name would misattribute
                # the result (known adapter or not: same honest refusal)
                return self._send(
                    404 if model_req not in self.engine.adapter_names
                    else 400,
                    {"error": {"message":
                               f"model {model_req!r} is not served by "
                               "/v1/embeddings (base model "
                               f"{self.engine.cfg.name!r} only)",
                               "type": "invalid_request_error"}})
            # encoding_format: the official openai-python client asks for
            # base64 by default (ADVICE r4: always answering float lists
            # breaks strict clients); unsupported ``dimensions`` is a loud
            # 400, not a silent ignore
            enc = req.get("encoding_format", "float")
            if enc not in ("float", "base64"):
                raise ValueError(
                    f"encoding_format must be 'float' or 'base64', "
                    f"got {enc!r}")
            dims = req.get("dimensions")
            if dims is not None and dims != self.engine.cfg.embed_dim:
                raise ValueError(
                    f"dimensions={dims} is not supported (embeddings are "
                    f"the model's hidden size, {self.engine.cfg.embed_dim})")
            raw = req.get("input")
            if raw is None:
                raise ValueError("missing input")
            if isinstance(raw, str) or (
                    isinstance(raw, list) and raw
                    and all(isinstance(t, int) for t in raw)):
                raw = [raw]
            if not isinstance(raw, list) or not raw:
                raise ValueError("input must be a non-empty string/list")
            data = []
            total_toks = 0
            for i, item in enumerate(raw):
                if isinstance(item, str):
                    if self.tokenizer is None:
                        raise ValueError("string input needs --tokenizer")
                    toks = self.tokenizer.encode(item)
                elif (isinstance(item, list) and item
                      and all(isinstance(t, int) for t in item)):
                    toks = item
                else:
                    raise ValueError(f"input[{i}] must be a string or a "
                                     "non-empty token list")
                total_toks += len(toks)
                vec = self.engine.embed(toks)
                if enc == "base64":
                    # little-endian f32 bytes, like the OpenAI API
                    import base64
                    import struct
                    vec = base64.b64encode(struct.pack(
                        f"<{len(vec)}f", *vec)).decode("ascii")
                data.append({"object": "embedding", "index": i,
                             "embedding": vec})
        except (json.JSONDecodeError, ValueError, TypeError,
                OverflowError) as e:
            return self._send(400, {"error": {"message": str(e),
                                              "type": "invalid_request_error"}})
        return self._send(200, {
            "object": "list", "data": data,
            "model": self.engine.cfg.name,
            "usage": {"prompt_tokens": total_toks,
                      "total_tokens": total_toks}})

    def _openai_completion(self, chat: bool):
        """OpenAI-compatible POST /v1/completions and /v1/chat/completions:
        lets existing OpenAI-SDK clients point at this server unchanged.
        Completions take prompt (string needs --tokenizer; token list
        always works) + optional logprobs; chat takes messages rendered
        through the model's own chat template when the HF tokenizer ships
        one (role-prefix fallback otherwise). Both support max_tokens,
        temperature, top_p, stop, and SSE streaming. The matched stop
        sequence (or EOS) never appears in the returned text, stream or
        not (OpenAI semantics) — streaming holds back the longest-possible
        stop tail until it is known not to be one."""
        try:
            req = self._read_json()
            tokens = self._request_tokens(
                "/v1/chat/completions" if chat else "/v1/completions", req)
            stop, stop_strs = self._parse_stop(req.get("stop"))
            n = req.get("n")
            n = 1 if n is None else n
            if not isinstance(n, int) or isinstance(n, bool) \
                    or not 1 <= n <= 16:
                raise ValueError(f"n must be an int in [1, 16], got {n!r}")
            if n > 1 and req.get("stream"):
                raise ValueError("streaming supports n=1")
            seed = req.get("seed")
            if seed is not None and (not isinstance(seed, int)
                                     or isinstance(seed, bool)):
                raise ValueError(f"seed must be an int, got {seed!r}")
            # logprobs: completions-only, non-stream only (SSE chunks don't
            # carry them — don't make the engine compute what we'd discard)
            want_lp = (bool(req.get("logprobs")) and not chat
                       and not req.get("stream"))
            # vLLM convention: with multi-LoRA enabled, the OpenAI "model"
            # field selects a registered adapter; the base model's own name
            # (or an absent field) serves the base, and an unknown name is a
            # 404 rather than silently serving the wrong tenant's weights.
            # WITHOUT multi-LoRA the field stays echo-only (clients often
            # send HF repo ids or placeholders — don't break them).
            model_req = req.get("model") or ""
            adapter = ""
            if (self.engine.multi_lora_enabled and model_req
                    and model_req != self.engine.cfg.name):
                if model_req not in self.engine.adapter_names:
                    return self._send(404, {"error": {
                        "message": f"model {model_req!r} does not exist "
                                   "(not the base model or a registered "
                                   "adapter)",
                        "type": "invalid_request_error"}})
                adapter = model_req
            kw = dict(max_new_tokens=req.get("max_tokens"),
                      temperature=_or(req.get("temperature"), 1.0),
                      top_p=_or(req.get("top_p"), 1.0), stop=stop,
                      stop_text=stop_strs,
                      presence_penalty=_or(req.get("presence_penalty"), 0.0),
                      frequency_penalty=_or(req.get("frequency_penalty"), 0.0),
                      logit_bias=req.get("logit_bias"),
                      logprobs=want_lp, adapter=adapter, seed=seed)
        except (json.JSONDecodeError, ValueError, TypeError) as e:
            return self._send(400, {"error": {"message": f"{e}",
                                              "type": "invalid_request_error"}})
        trace_kw, trace_hdrs = self._trace_ctx()
        kw.update(trace_kw)
        kw.update(self._tenant_kw(req))
        # ns-scale wall stamp + process-wide counter: unique even when an
        # injected test clock stands still
        ns = int(self.clock() * 1e9) + next(_RID_SEQ)
        rid = f"chatcmpl-{ns:x}" if chat else f"cmpl-{ns:x}"
        created = int(self.clock())
        model_name = req.get("model") or self.engine.cfg.name
        obj = "chat.completion" if chat else "text_completion"

        def finish_reason(toks: list) -> tuple[str, list]:
            """(reason, tokens with any matched stop/EOS tail stripped)."""
            for s in stop:
                if len(s) <= len(toks) and toks[-len(s):] == s:
                    return "stop", toks[:-len(s)]
            if toks and toks[-1] == self.engine.sc.eos_token:
                return "stop", toks[:-1]
            return "length", toks

        def decode(toks: list) -> str:
            return (self.tokenizer.decode(toks) if self.tokenizer is not None
                    else "")

        def finish_text(all_toks: list) -> tuple[str, list, str]:
            """(reason, stripped tokens, final text) — token-level strip
            first, then the BPE-exact text cut at the first stop-string
            occurrence (a straddling stop survives the token strip but
            must still never reach the client)."""
            reason, toks = finish_reason(all_toks)
            text = decode(toks)
            if stop_strs:
                text, hit = self._cut_at_stop(text, stop_strs)
                if hit:
                    reason = "stop"
            return reason, toks, text

        first_chunk = [True]

        def chunk_obj(text: str, reason=None) -> dict:
            if chat:
                delta: dict = {"content": text} if text else {}
                if first_chunk[0]:
                    delta = {"role": "assistant", **delta}
                    first_chunk[0] = False
                choice = {"delta": delta, "index": 0, "finish_reason": reason}
                return {"id": rid, "object": "chat.completion.chunk",
                        "created": created, "model": model_name,
                        "choices": [choice]}
            return {"id": rid, "object": "text_completion",
                    "created": created, "model": model_name,
                    "choices": [{"text": text, "index": 0,
                                 "finish_reason": reason}]}

        def sse(payload) -> bytes:
            data = payload if isinstance(payload, str) else json.dumps(payload)
            return f"data: {data}\n\n".encode()

        if req.get("stream"):
            # hold back the longest tail that could still become a stop/EOS
            # match, so stop text never reaches the client
            holdback = max([len(s) for s in stop] or [0])
            if self.engine.sc.eos_token >= 0:
                holdback = max(holdback, 1)
            pending: list = []   # tokens still inside the stop-tail window
            released: list = []  # tokens cleared for emission, cumulative
            sent = [0]           # chars of decode(released) already streamed
            # text-exact stops additionally hold back the longest stop-
            # string length - 1 CHARS: a partial stop at the text tail may
            # still complete, and emitted text can't be retracted
            char_hold = max([len(s) for s in stop_strs] or [1]) - 1
            text_hit = [False]   # a stop string appeared in decoded text

            def text_delta(final: bool) -> str:
                """Incremental decode by cumulative diff: per-fragment
                decode would corrupt multi-byte UTF-8 chars (and BPE
                word-boundary merges) split across chunks. A trailing
                U+FFFD may be an incomplete char mid-stream — hold it
                until more bytes arrive (or the stream ends)."""
                text = decode(released)
                if not final and text.endswith("�"):
                    text = text[:-1]
                if stop_strs:
                    cut, hit = self._cut_at_stop(text, stop_strs)
                    if hit:
                        text_hit[0] = True
                        text = cut
                    elif not final and char_hold:
                        text = text[:max(sent[0], len(text) - char_hold)]
                delta = text[sent[0]:]
                sent[0] += len(delta)
                return delta

            def fmt_token(t) -> list:
                pending.append(t)
                if len(pending) > holdback:
                    released.extend(pending[:len(pending) - holdback])
                    del pending[:len(pending) - holdback]
                    delta = text_delta(final=False)
                    if delta:
                        return [sse(chunk_obj(delta))]
                return []

            def fmt_end(out) -> list:
                reason, stripped = finish_reason(out["tokens"])
                n_strip = len(out["tokens"]) - len(stripped)
                released.extend(pending[:len(pending) - n_strip]
                                if n_strip else pending)
                bodies = []
                delta = text_delta(final=True)
                if text_hit[0]:  # BPE text stop fired (or is being cut now)
                    reason = "stop"
                if delta:
                    bodies.append(sse(chunk_obj(delta)))
                bodies.append(sse(chunk_obj("", reason)))
                bodies.append(sse("[DONE]"))
                return bodies

            def fmt_start() -> list:
                # chat: lead with the role delta (OpenAI's own first chunk)
                return [sse(chunk_obj(""))] if chat else []

            return self._stream_pump(
                tokens, kw, "text/event-stream",
                {"token": fmt_token,
                 "end": fmt_end,
                 "start": fmt_start,
                 "timeout": lambda: [sse({"error": {
                     "message": "generation timed out",
                     "type": "timeout"}}), sse("[DONE]")],
                 "error": lambda msg: [sse({"error": {
                     "message": msg, "type": "server_error"}}), sse("[DONE]")],
                 "badreq": lambda msg: {"error": {
                     "message": msg, "type": "invalid_request_error"}},
                 # same condition as _overloaded(): an SDK client branching
                 # on type must see a retryable overload, not a bad request
                 "overloaded": lambda msg: {"error": {
                     "message": msg, "type": "overloaded_error"}}},
                extra_headers=trace_hdrs)

        # n choices share ONE prefill (the engine fans the cache out); with
        # an explicit seed each choice offsets it so the samples differ
        # (OpenAI's n returns distinct samples, not n copies)
        base_seed = kw.pop("seed", None)
        futs = self.engine.submit_group(tokens, n, seed=base_seed, **kw)
        deadline = self.mono() + self.request_timeout_s  # SHARED:
        # per-future timeouts would let n=16 hold the connection 16x longer
        try:
            outs = [f.result(timeout=max(0.0, deadline - self.mono()))
                    for f in futs]
        except FutureTimeout:
            for f in futs:
                f.cancel()  # engine frees the slots at their next step
            return self._send(504, {"error": {"message": "generation timed out",
                                              "type": "timeout"}})
        except ValueError as e:
            for f in futs:
                f.cancel()
            return self._send(400, {"error": {"message": str(e),
                                              "type": "invalid_request_error"}})
        except Exception as e:  # engine crash (e.g. recovery-path RuntimeError)
            for f in futs:
                f.cancel()
            from .serving import EngineDraining, EngineOverloaded
            if isinstance(e, (EngineOverloaded, EngineDraining)):
                return self._overloaded(e, openai=True)
            return self._send(500, {"error": {"message": str(e),
                                              "type": "server_error"}})
        choices = []
        for i, out in enumerate(outs):
            reason, toks, text = finish_text(out["tokens"])
            if chat:
                choice: dict = {"index": i, "finish_reason": reason,
                                "message": {"role": "assistant",
                                            "content": text}}
            else:
                choice = {"text": text, "index": i,
                          "logprobs": None, "finish_reason": reason}
                if kw["logprobs"]:
                    choice["logprobs"] = {
                        "token_logprobs": out.get("logprobs", [])[:len(toks)],
                        "tokens": [decode([t]) for t in toks],
                        "top_logprobs": None}
            choices.append(choice)
        gen_tokens = sum(len(o["tokens"]) for o in outs)
        return self._send(200, {
            "id": rid, "object": obj, "created": created,
            "model": model_name, "choices": choices,
            "usage": {"prompt_tokens": len(tokens),
                      "completion_tokens": gen_tokens,
                      "total_tokens": len(tokens) + gen_tokens}},
            extra_headers=trace_hdrs)

    def _generate_stream(self, tokens: list, req: dict):
        """Chunked NDJSON over the shared pump: one {"token": N} line per
        decoded token, then the final result object (or {"error": ...})."""
        try:
            stop, stop_strs = self._parse_stop(req.get("stop"))
        except ValueError as e:
            return self._send(400, {"error": str(e)})
        trace_kw, trace_hdrs = self._trace_ctx()
        kw = dict(max_new_tokens=req.get("max_new_tokens"),
                  temperature=req.get("temperature"),
                  top_k=_or(req.get("top_k"), 0),
                  top_p=_or(req.get("top_p"), 1.0), stop=stop,
                  stop_text=stop_strs,
                  presence_penalty=_or(req.get("presence_penalty"), 0.0),
                  frequency_penalty=_or(req.get("frequency_penalty"), 0.0),
                  logit_bias=req.get("logit_bias"),
                  adapter=req.get("adapter") or "", seed=req.get("seed"),
                  **trace_kw, **self._tenant_kw(req))

        def line(payload: dict) -> bytes:
            return (json.dumps(payload) + "\n").encode()

        kw["logprobs"] = bool(req.get("logprobs"))

        def fmt_end(out) -> list:
            if self.tokenizer is not None:
                out = dict(out)
                text = self.tokenizer.decode(out["tokens"])
                if stop_strs:  # raw token lines already streamed; the
                    # text field honors the text-exact stop
                    text, _ = self._cut_at_stop(text, stop_strs)
                out["text"] = text
            return [line(out)]

        return self._stream_pump(
            tokens, kw, "application/x-ndjson",
            {"token": lambda t: [line({"token": t})],
             "end": fmt_end,
             "timeout": lambda: [line({"error": "generation timed out"})],
             "error": lambda msg: [line({"error": msg})],
             "badreq": lambda msg: {"error": msg}},
            extra_headers=trace_hdrs)


class BoundedThreadingHTTPServer(ThreadingHTTPServer):
    """Thread-per-connection with a hard concurrency bound (r3 VERDICT weak
    item 7: the stdlib server piles up unbounded threads under real load).
    Beyond ``max_connections`` in-flight connections, new ones get an
    immediate 503 + Retry-After on the raw socket — no handler thread, no
    engine work — so overload degrades crisply instead of by fd/thread
    exhaustion. The engine's slot queue is the MODEL-level backpressure;
    this bounds the HTTP layer itself (idle keep-alives, slowloris).

    Observability stays alive under overload: when the main pool is full,
    GET /metrics and /healthz (recognized by a non-consuming MSG_PEEK at
    the request line) ride a small reserved pool — the scrape that should
    SEE the incident must not be shed by it."""

    _REJECT = (b"HTTP/1.1 503 Service Unavailable\r\n"
               b"Retry-After: 1\r\n"
               b"Content-Type: application/json\r\n"
               b"Content-Length: 31\r\n"
               b"Connection: close\r\n\r\n"
               b'{"error": "server overloaded"}\n')
    _OBS_RESERVE = 2

    def __init__(self, addr, handler, max_connections: int = 128,
                 mono=time.monotonic):
        super().__init__(addr, handler)
        self._mono = mono  # deadline source for overflow triage (injectable)
        self.max_connections = max_connections
        self._conn_sem = threading.BoundedSemaphore(max_connections)
        self._obs_sem = threading.BoundedSemaphore(self._OBS_RESERVE)
        self._req_sem: dict[int, threading.BoundedSemaphore] = {}

    def _is_observability(self, request) -> bool:
        import socket as _socket
        try:
            request.settimeout(0.3)
            head = request.recv(64, _socket.MSG_PEEK)
            return head.startswith((b"GET /metrics", b"GET /healthz"))
        except OSError:
            return False
        finally:
            try:
                request.settimeout(None)
            except OSError:
                pass

    def process_request(self, request, client_address):
        if self._conn_sem.acquire(blocking=False):
            self._req_sem[id(request)] = self._conn_sem
            try:
                super().process_request(request, client_address)
            except BaseException:  # thread spawn failed: slot must not leak
                self._req_sem.pop(id(request), None)
                self._conn_sem.release()
                raise
            return
        # Overload: triage OFF the accept thread — the peek and the reject
        # drain both wait on the peer, and one slow peer must never stall
        # serve_forever's accept loop (that would shed /metrics too, the
        # exact failure the reserve exists to prevent). Triage threads are
        # short-lived (~1s bounded) and only exist while overloaded.
        threading.Thread(target=self._triage_overflow,
                         args=(request, client_address), daemon=True).start()

    def _triage_overflow(self, request, client_address):
        if (self._is_observability(request)
                and self._obs_sem.acquire(blocking=False)):
            self._req_sem[id(request)] = self._obs_sem
            # already on a dedicated thread: run the handler directly
            self.process_request_thread(request, client_address)
            return
        try:
            engine = getattr(self.RequestHandlerClass, "engine", None)
            if engine is not None:
                engine.metrics.incr("tpu_serving_http_rejected")
        except Exception:  # noqa: BLE001 — metrics must never block 503
            pass
        try:
            request.sendall(self._REJECT)
            # drain so close doesn't RST away the buffered 503 — bounded
            # by wall time AND bytes (a dribbling client must not pin the
            # thread; each recv would otherwise reset the timeout)
            deadline = self._mono() + 1.0
            drained = 0
            request.settimeout(0.25)
            try:
                while self._mono() < deadline and drained < 65536:
                    data = request.recv(4096)
                    if not data:
                        break
                    drained += len(data)
            except OSError:
                pass
        except OSError:
            pass
        self.shutdown_request(request)

    def process_request_thread(self, request, client_address):
        try:
            super().process_request_thread(request, client_address)
        finally:
            sem = self._req_sem.pop(id(request), None)
            if sem is not None:
                sem.release()


def serve(engine, port: int = 8000, request_timeout_s: float = 120.0,
          tokenizer=None, allow_adapters: bool = False,
          max_connections: int = 128, handoff_stream_window: int = 8,
          device_domain: str = "", pull_timeout_s: float = 10.0,
          profile_capture: bool = False,
          clock=time.time, mono=time.monotonic):
    # described here, not in the engine: the HTTP-layer shed counter belongs
    # to this server (the engine never sees the rejected connection)
    engine.metrics.describe(
        "tpu_serving_http_rejected",
        "connections 503-shed at the HTTP concurrency bound")
    # owner-side shm-blob GC for the pull path: only a replica in a
    # placement domain can be asked for via=shm pulls (ISSUE 16)
    shm_gc = None
    if device_domain:
        from ..fleet.device_transfer import ShmBlobGC
        shm_gc = ShmBlobGC(clock=mono)
    handler = type("BoundHandler", (_Handler,),
                   {"engine": engine, "request_timeout_s": request_timeout_s,
                    "tokenizer": tokenizer, "allow_adapters": allow_adapters,
                    "handoff_stream_window": handoff_stream_window,
                    "device_domain": device_domain,
                    "pull_timeout_s": pull_timeout_s, "shm_gc": shm_gc,
                    "profile_capture": profile_capture,
                    "clock": staticmethod(clock), "mono": staticmethod(mono)})
    httpd = BoundedThreadingHTTPServer(("0.0.0.0", port), handler,
                                       max_connections=max_connections,
                                       mono=mono)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return httpd


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    from ..models import MODEL_CONFIGS
    serveable = [n for n in MODEL_CONFIGS if n != "deepseek-v3"]
    p.add_argument("--model", default="gemma-7b", choices=serveable)
    # deepseek-v3 (671B) is multi-host-only: convertible/testable via the
    # registry but not a single-replica serve target
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--cache-len", type=int, default=2048)
    p.add_argument("--max-new-tokens", type=int, default=256)
    p.add_argument("--tokenizer", default="",
                   help='"bytes" (UTF-8 byte ids, any model with vocab>=257) '
                        "or a HuggingFace tokenizer directory; enables "
                        '{"text": ...} requests and decoded responses')
    p.add_argument("--speculate", type=int, default=0,
                   help="speculative decoding: draft this many tokens per "
                        "step via prompt-lookup and verify in one pass "
                        "(exact greedy output, lower latency on repetitive "
                        "text); 0 = off")
    p.add_argument("--int8", action="store_true",
                   help="weight-only int8 quantization (halves decode HBM "
                        "traffic; JetStream-style serving optimization)")
    p.add_argument("--int4", action="store_true",
                   help="weight-only int4 quantization (group-wise scales, "
                        "two weights per byte): quarter decode weight "
                        "traffic — the rung after --int8; run an eval "
                        "before production, 4-bit costs more accuracy")
    p.add_argument("--kv-int8", action="store_true",
                   help="int8 KV cache with per-position scales (halves "
                        "cache HBM traffic and doubles slot capacity)")
    p.add_argument("--lora-rank", type=int, default=0,
                   help="enable multi-LoRA serving at this adapter rank; "
                        "register adapters via POST /adapters and select "
                        'per request with "adapter" (or the OpenAI "model" '
                        "field)")
    p.add_argument("--lora-targets", default="wq,wv",
                   help="projections the adapters cover (must match how "
                        "they were trained)")
    p.add_argument("--max-adapters", type=int, default=8)
    p.add_argument("--dynamic-adapters", action="store_true",
                   help="enable POST /adapters (live adapter registration "
                        "from a server-readable .npz path) — off by "
                        "default because it lets API clients load "
                        "filesystem paths and replace live tenant weights")
    p.add_argument("--ring-cache", default=None,
                   choices=["auto", "on", "off"],
                   help="ring KV cache for sliding-window models: physical "
                        "cache shrinks to ~window while --cache-len stays "
                        "the logical budget (default auto)")
    p.add_argument("--tensor-parallel", type=int, default=1,
                   help="shard the model over this many chips (tensor "
                        "parallelism): params by the logical-axis rules, "
                        "KV cache on its kv-heads axis — 70B-class serving "
                        "spans a slice this way")
    p.add_argument("--expert-parallel", type=int, default=1,
                   help="shard MoE expert weights over this many chips "
                        "(expert parallelism; composes with "
                        "--tensor-parallel, e.g. EP4xTP2 on 8 chips): each "
                        "chip holds n_experts/EP experts — the per-chip "
                        "memory lever for 256-expert-class models")
    p.add_argument("--max-connections", type=int, default=128,
                   help="HTTP-layer concurrency bound: connections beyond "
                        "this get an immediate 503 + Retry-After (the HPA "
                        "scale signal stays the engine queue depth)")
    p.add_argument("--max-queue-depth", type=int, default=0,
                   help="engine admission bound: requests beyond this many "
                        "queued get 429 + Retry-After instead of an "
                        "unbounded wait (0 = unbounded; HPA still scales "
                        "on tpu_serving_queue_depth)")
    p.add_argument("--config", default="",
                   help="provider-config YAML: serving reads the paged-KV "
                        "knobs (kv_page_tokens / kv_pool_pages / "
                        "prefix_cache_enabled) from it; TPU_KV_* env "
                        "overrides the file, these flags override both")
    p.add_argument("--kv-page-tokens", type=int, default=None,
                   dest="kv_page_tokens",
                   help="tokens per KV page in the paged prefix pool (the "
                        "allocation and trie-match granule; default from "
                        "config/TPU_KV_PAGE_TOKENS, 16)")
    p.add_argument("--kv-pool-pages", type=int, default=None,
                   dest="kv_pool_pages",
                   help="pages in the preallocated prefix arena (0 = auto: "
                        "one decode-cache's worth; default from config/"
                        "TPU_KV_POOL_PAGES)")
    p.add_argument("--prefix-cache", default=None, choices=["on", "off"],
                   dest="prefix_cache_enabled",
                   help="cross-request paged prefix cache: every prompt "
                        "matches a radix trie of shared KV pages and skips "
                        "the matched span's prefill (default from config/"
                        "TPU_PREFIX_CACHE_ENABLED, on; register_prefix "
                        "works either way)")
    p.add_argument("--paged-decode", default=None, choices=["auto", "off"],
                   dest="kv_paged_decode",
                   help="decode hot loop on per-slot page tables over the "
                        "shared arena: prefix hits and handed-off KV are "
                        "referenced zero-copy (default from config/"
                        "TPU_KV_PAGED_DECODE, auto — on whenever the "
                        "model/layout allows it; tensor-parallel engines "
                        "included — the arena shards over the mesh)")
    p.add_argument("--paged-prefill", default=None, choices=["auto", "off"],
                   dest="kv_paged_prefill",
                   help="paged-native prefill: scatter prefill chunks "
                        "straight into the slot's arena pages — no dense "
                        "scratch cache or page copy on the hot path "
                        "(default from config/TPU_KV_PAGED_PREFILL, auto — "
                        "on whenever the paged decode loop runs; off keeps "
                        "the dense-scratch + adoption-copy route)")
    p.add_argument("--kv-arena-sharding", default=None,
                   choices=["auto", "replicate"],
                   dest="kv_arena_sharding",
                   help="paged-arena placement under --tensor-parallel: "
                        "auto shards the kv-heads axis over the mesh like "
                        "the contiguous cache (MLA latents replicate), "
                        "replicate pins every shard a full arena copy — "
                        "pays HBM, keeps paged decode on odd geometries "
                        "(default from config/TPU_KV_ARENA_SHARDING, auto)")
    p.add_argument("--serving-chunk-tokens", type=int, default=None,
                   dest="serving_chunk_tokens",
                   help="chunked prefill: process prompts in chunks of "
                        "this many tokens, interleaving decode steps "
                        "between chunks (bounds co-resident streams' ITL "
                        "under long prefills) and streaming each chunk's "
                        "KV pages during disaggregated handoffs; 0 = "
                        "monolithic (default from config/"
                        "TPU_SERVING_CHUNK_TOKENS)")
    p.add_argument("--handoff-stream-window", type=int, default=None,
                   dest="handoff_stream_window",
                   help="streamed handoff: max chunk frames queued "
                        "between prefill compute and the push to the "
                        "decode replica — the compute/transfer overlap "
                        "window (default from config/"
                        "TPU_HANDOFF_STREAM_WINDOW, 8)")
    p.add_argument("--serving-role", default=None, dest="serving_role",
                   choices=["unified", "prefill", "decode"],
                   help="disaggregated-serving pool this replica registers "
                        "into: prefill computes KV and hands pages off, "
                        "decode adopts KV and streams tokens, unified does "
                        "both (default from config/TPU_SERVING_ROLE, "
                        "unified)")
    p.add_argument("--device-transfer", default=None, choices=["on", "off"],
                   dest="fleet_device_transfer_enabled",
                   help="device-native KV handoff: co-located replicas "
                        "(same placement domain) move pages arena-to-arena "
                        "with zero host copies; any device-path failure "
                        "downgrades to the wire codec (default from "
                        "config/TPU_FLEET_DEVICE_TRANSFER_ENABLED, on)")
    p.add_argument("--placement-domain", default=None,
                   dest="fleet_placement_domain",
                   help="placement domain this replica advertises for "
                        "device-native handoffs; replicas with EQUAL "
                        "domains hand device buffers directly (default "
                        "from config/TPU_FLEET_PLACEMENT_DOMAIN, else "
                        "auto-detected as proc:<host>:<pid> — the "
                        "co-location the in-process bus can serve)")
    p.add_argument("--placement-domain-mode", default=None,
                   dest="fleet_placement_domain_mode",
                   choices=["auto", "proc", "slice"],
                   help="how the placement domain auto-detects when no "
                        "explicit domain is set: 'auto' prefers the gang "
                        "scheduler's slice identity (TPU_SLICE_NAME, "
                        "host-qualified) and falls back to the process "
                        "domain; 'slice' warns when the slice identity is "
                        "missing; 'proc' pins one-process-per-domain")
    p.add_argument("--pull-timeout", type=float, default=None,
                   dest="fleet_pull_timeout_s",
                   help="budget in seconds for one KV directory-pull hop "
                        "(owner export + transfer + adoption); default "
                        "from config/TPU_FLEET_PULL_TIMEOUT_S")
    p.add_argument("--hf-checkpoint", default="",
                   help="HuggingFace model directory (safetensors/bin) to "
                        "load real weights from; empty = random init")
    p.add_argument("--flight-recorder", default=None, choices=["on", "off"],
                   dest="serving_flight_recorder",
                   help="per-decode-step flight recorder: a bounded ring "
                        "of step records (batch composition, schedule/"
                        "kernel/sample/commit phase split, arena page "
                        "counts, speculative accounting) at GET "
                        "/debug/steps, folded into serving.request spans "
                        "(default from config/"
                        "TPU_SERVING_FLIGHT_RECORDER, on)")
    p.add_argument("--cost-meter", default=None, choices=["on", "off"],
                   dest="serving_cost_meter",
                   help="per-request chip-second/dollar attribution "
                        "(ISSUE 20): phase walls priced via the "
                        "generations.py table, per-tenant ledger at GET "
                        "/debug/costs, zero-seeded cost metrics, span "
                        "cost attrs (default from config/"
                        "TPU_SERVING_COST_METER, on)")
    p.add_argument("--profiler-port", type=int, default=None,
                   dest="serving_profiler_port",
                   help="start the on-demand jax.profiler server on this "
                        "port (parity with train_main): connect TensorBoard "
                        "or `jax.profiler.trace_server` tooling for live "
                        "captures; 0 = off (default from config/"
                        "TPU_SERVING_PROFILER_PORT)")
    p.add_argument("--profile-capture", default=None, choices=["on", "off"],
                   dest="serving_profile_capture",
                   help="enable GET /debug/profile?seconds= trace captures "
                        "(writes a jax.profiler trace on the replica's "
                        "disk); off by default because any API client "
                        "could otherwise stall the device (default from "
                        "config/TPU_SERVING_PROFILE_CAPTURE)")
    p.add_argument("--trace-export", default="",
                   help="append finished request spans to this JSONL file "
                        "(render with tools/trace_summary.py); empty = "
                        "in-memory ring only (/debug/traces)")
    p.add_argument("--fleet-router", default="",
                   help="fleet router URL (fleet/router_main.py): register "
                        "this replica and heartbeat load stats so the "
                        "router balances traffic here; empty = standalone")
    p.add_argument("--fleet-advertise", default="",
                   help="URL the ROUTER should reach this replica at "
                        "(e.g. http://$POD_IP:8000); defaults to "
                        "http://<hostname>:<port>")
    p.add_argument("--fleet-replica-id", default="",
                   help="stable replica identity; defaults to the hostname "
                        "(= pod name in k8s)")
    p.add_argument("--fleet-heartbeat-interval", type=float, default=2.0,
                   help="seconds between heartbeats to the fleet router")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    import jax
    from ..config import load as load_provider_config
    from ..models import init_params
    from .serving import ServingConfig, ServingEngine
    # paged-KV knob precedence: flag > TPU_KV_* env > --config file >
    # defaults — load() already applies env-over-file, flags land here
    base_cfg = load_provider_config(args.config or None)
    kv_page_tokens = (args.kv_page_tokens if args.kv_page_tokens is not None
                      else base_cfg.kv_page_tokens)
    kv_pool_pages = (args.kv_pool_pages if args.kv_pool_pages is not None
                     else base_cfg.kv_pool_pages)
    prefix_cache_enabled = (base_cfg.prefix_cache_enabled
                            if args.prefix_cache_enabled is None
                            else args.prefix_cache_enabled == "on")
    # paged decode: config True = auto (engine decides eligibility),
    # False pins the contiguous loop; the flag overrides either way
    kv_paged_decode = (base_cfg.kv_paged_decode
                       if args.kv_paged_decode is None
                       else args.kv_paged_decode == "auto")
    kv_paged_prefill = (base_cfg.kv_paged_prefill
                        if args.kv_paged_prefill is None
                        else args.kv_paged_prefill == "auto")
    kv_arena_sharding = args.kv_arena_sharding or base_cfg.kv_arena_sharding
    serving_role = args.serving_role or base_cfg.serving_role
    serving_chunk_tokens = (args.serving_chunk_tokens
                            if args.serving_chunk_tokens is not None
                            else base_cfg.serving_chunk_tokens)
    handoff_stream_window = (args.handoff_stream_window
                             if args.handoff_stream_window is not None
                             else base_cfg.handoff_stream_window)
    # device-native handoff (ISSUE 11): flag > env/config; the domain
    # auto-detects to this process when nothing overrides it
    from ..fleet.device_transfer import detect_placement_domain
    device_transfer = (base_cfg.fleet_device_transfer_enabled
                       if args.fleet_device_transfer_enabled is None
                       else args.fleet_device_transfer_enabled == "on")
    placement_domain_mode = (args.fleet_placement_domain_mode
                             or base_cfg.fleet_placement_domain_mode)
    placement_domain = detect_placement_domain(
        args.fleet_placement_domain or base_cfg.fleet_placement_domain,
        mode=placement_domain_mode) \
        if device_transfer else ""
    pull_timeout_s = (args.fleet_pull_timeout_s
                      if args.fleet_pull_timeout_s is not None
                      else base_cfg.fleet_pull_timeout_s)
    # observability knobs (ISSUE 17): flag > TPU_SERVING_* env > config
    flight_recorder = (base_cfg.serving_flight_recorder
                       if args.serving_flight_recorder is None
                       else args.serving_flight_recorder == "on")
    cost_meter = (base_cfg.serving_cost_meter
                  if args.serving_cost_meter is None
                  else args.serving_cost_meter == "on")
    profiler_port = (args.serving_profiler_port
                     if args.serving_profiler_port is not None
                     else base_cfg.serving_profiler_port)
    profile_capture = (base_cfg.serving_profile_capture
                       if args.serving_profile_capture is None
                       else args.serving_profile_capture == "on")
    if profiler_port:
        jax.profiler.start_server(profiler_port)
        log.info("jax profiler server on :%d", profiler_port)
    cfg = MODEL_CONFIGS[args.model]()
    log.info("loading %s (%.2fB params) on %s", cfg.name,
             cfg.param_count / 1e9, jax.default_backend())
    from .tokenizer import get_tokenizer
    tokenizer = get_tokenizer(args.tokenizer)  # before the expensive load:
    # a bad --tokenizer path must fail fast, not after minutes of weights
    if args.int8 and args.int4:
        log.error("--int8 and --int4 are mutually exclusive — pick one "
                  "weight precision")
        return 1
    if args.lora_rank > 0 and cfg.is_mla:
        log.error("--lora-rank does not compose with MLA models (adapters "
                  "target the wq/wk/wv layout; %s uses w_dkv/w_uk/w_uv)",
                  cfg.name)
        return 1
    mesh = None
    if args.tensor_parallel < 1 or args.expert_parallel < 1:
        # validated OUTSIDE the mesh gate: a 0/negative degree must error
        # here, not silently fall through to unsharded single-chip serving
        log.error("--tensor-parallel and --expert-parallel must be >= 1 "
                  "(got %d, %d)", args.tensor_parallel, args.expert_parallel)
        return 1
    if args.tensor_parallel > 1 or args.expert_parallel > 1:
        # fail-fast BEFORE the expensive weight load, like the tokenizer
        # check above
        from ..parallel import MeshConfig, make_mesh
        n = args.tensor_parallel
        ep = args.expert_parallel
        if ep > 1 and (not cfg.n_experts or cfg.n_experts % ep):
            log.error("--expert-parallel %d needs an MoE model whose "
                      "n_experts it divides (%s has n_experts=%d)",
                      ep, cfg.name, cfg.n_experts)
            return 1
        if cfg.n_kv_heads % n or cfg.n_heads % n:
            log.error("--tensor-parallel %d must divide the model's head "
                      "counts (n_heads=%d, n_kv_heads=%d)",
                      n, cfg.n_heads, cfg.n_kv_heads)
            return 1
        if len(jax.devices()) < n * ep:
            log.error("--tensor-parallel %d x --expert-parallel %d but jax "
                      "sees %d device(s)", n, ep, len(jax.devices()))
            return 1
        mesh = make_mesh(MeshConfig(data=1, expert=ep, tensor=n),
                         jax.devices()[:n * ep])
        log.info("sharded serving: expert=%d tensor=%d over %s", ep, n,
                 jax.devices()[:n * ep])
    if args.hf_checkpoint:
        from ..models import load_hf
        params = load_hf(cfg, args.hf_checkpoint)  # host tree
        if mesh is not None and not (args.int8 or args.int4):
            from ..models import param_logical_axes
            from ..parallel import param_shardings
            params = jax.device_put(
                params, param_shardings(mesh, param_logical_axes(cfg)))
        elif not (args.int8 or args.int4):
            # (mesh + --int8 keeps the HOST tree: the engine quantizes it
            # and device_puts the int8 leaves with quantized_logical_axes
            # shardings — the bf16 tree never occupies a whole chip)
            # one device_put (serving is single-host per replica); with
            # --int8/--int4 the engine quantizes from host instead, so the
            # full-precision tree never occupies HBM next to the quantized
            # copy
            params = jax.device_put(params)
    else:
        params = init_params(cfg, jax.random.PRNGKey(0), mesh)
    engine = ServingEngine(cfg, params, ServingConfig(
        slots=args.slots, cache_len=args.cache_len,
        max_new_tokens=args.max_new_tokens,
        max_prefill_len=args.cache_len // 2,
        quantize_int8=args.int8,
        quantize_int4=args.int4,
        quantize_kv_int8=args.kv_int8,
        lora_rank=args.lora_rank,
        lora_targets=tuple(t for t in args.lora_targets.split(",") if t),
        max_adapters=args.max_adapters,
        ring_cache={None: None, "auto": None, "on": True,
                    "off": False}[args.ring_cache],
        speculate_k=args.speculate,
        max_queue_depth=args.max_queue_depth,
        kv_page_tokens=kv_page_tokens,
        kv_pool_pages=kv_pool_pages,
        prefix_cache_enabled=prefix_cache_enabled,
        paged_decode=None if kv_paged_decode else False,
        paged_prefill=None if kv_paged_prefill else False,
        kv_arena_sharding=kv_arena_sharding,
        serving_chunk_tokens=serving_chunk_tokens,
        flight_recorder=flight_recorder,
        cost_meter=cost_meter,
        # text mode stops at the tokenizer's EOS instead of always burning
        # the full max_new_tokens budget
        eos_token=(tokenizer.eos_id if tokenizer is not None else -1)),
        # decoded-text stop matching (BPE-exact stops) needs the engine
        # to see text, not just token ids
        decode_fn=(tokenizer.decode if tokenizer is not None else None),
        mesh=mesh,
        tracer=Tracer(export_path=args.trace_export)).start()
    httpd = serve(engine, args.port, tokenizer=tokenizer,
                  allow_adapters=args.dynamic_adapters,
                  max_connections=args.max_connections,
                  handoff_stream_window=handoff_stream_window,
                  device_domain=placement_domain,
                  pull_timeout_s=pull_timeout_s,
                  profile_capture=profile_capture)
    log.info("serving on :%d (POST /generate, GET /metrics)", args.port)
    import socket
    host = socket.gethostname()
    advertise_url = args.fleet_advertise or f"http://{host}:{args.port}"
    if placement_domain:
        # same-domain prefill replicas resolve this engine by the URL the
        # router hands them for the wire push — the two paths share one
        # address per replica, so a hop can downgrade without re-planning
        from ..fleet.device_transfer import BUS
        BUS.register(advertise_url, engine, placement_domain)
        log.info("device transfer: %s registered in domain %s",
                 advertise_url, placement_domain)
    reporter = None
    if args.fleet_router:
        from ..fleet.registry import ReplicaReporter
        reporter = ReplicaReporter(
            engine, args.fleet_router,
            replica_id=args.fleet_replica_id or host,
            advertise_url=advertise_url,
            # pod_name is the autoscaler's DELETE handle and must be the
            # real k8s pod name (= hostname), NOT the free-form replica
            # id: a custom --fleet-replica-id would otherwise make
            # scale-down delete a nonexistent pod (404 swallowed) and
            # leak the real one
            pod_name=host,
            interval_s=args.fleet_heartbeat_interval,
            role=serving_role,
            placement_domain=placement_domain,
            # mixed-fleet identity (ISSUE 19): the scheduler-aware pod
            # scaler stamps these into the pod env at creation so the
            # replica registers with the generation/pool its chips were
            # reserved on — heartbeats then refine the right cell of the
            # throughput matrix
            generation=os.environ.get("TPU_SERVING_GENERATION", ""),
            pool=os.environ.get("TPU_SERVING_POOL", "")).start()
        if base_cfg.fleet_prefix_directory_enabled:
            # publish-on-trie-insert (ISSUE 16): a fresh prefix key wakes
            # the reporter so the directory learns about it on the NEXT
            # beat, not up to a full interval later
            engine.prefix_publish_hook = reporter.wake
        log.info("fleet: reporting to %s as %s (role %s)",
                 args.fleet_router, reporter.replica_id, serving_role)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    if reporter is not None:
        reporter.stop()
    httpd.shutdown()
    engine.stop()
    engine.tracer.close()  # flush the JSONL export queue (daemon writer)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
