"""Tokenizers for the serving front end: text in, text out.

The engine itself speaks token ids (the JetStream shape); this adapts the
HTTP surface for human clients:

- ``ByteTokenizer``: dependency-free UTF-8 byte tokenizer (id = byte value).
  Works with any model whose vocab >= 256 — the hermetic-test / smoke-demo
  tokenizer, and a sane default for random-weight models.
- ``HfTokenizer``: wraps a HuggingFace tokenizer directory
  (``transformers.AutoTokenizer``) for real checkpoints — pairs with
  ``--hf-checkpoint`` so text round-trips through the model's true vocab.

``get_tokenizer("bytes")`` or ``get_tokenizer("/path/to/hf_dir")``.
"""

from __future__ import annotations

from typing import Optional, Protocol

__all__ = ["ByteTokenizer", "HfTokenizer", "get_tokenizer"]


class Tokenizer(Protocol):
    def encode(self, text: str) -> list[int]: ...
    def decode(self, tokens: list[int]) -> str: ...
    @property
    def eos_id(self) -> int: ...


def _chat_fallback_text(messages: list[dict]) -> str:
    """Shared minimal chat template: "role: content" lines + assistant cue
    (used whenever no model-native chat template exists)."""
    return "".join(f"{m['role']}: {m['content']}\n" for m in messages) \
        + "assistant:"


class ByteTokenizer:
    """UTF-8 bytes as token ids (0..255); id 256 = EOS. Lossless round-trip
    for any text; needs model vocab >= 257 (EOS optional at >= 256)."""

    vocab_size = 257
    # token-level stop matching is already text-exact here: every string
    # has exactly one tokenization, so no decoded-text fallback is needed
    byte_exact = True

    @property
    def eos_id(self) -> int:
        return 256

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def encode_plain(self, text: str) -> list[int]:
        """No special tokens — for stop-sequence matching."""
        return self.encode(text)

    def decode(self, tokens: list[int]) -> str:
        return bytes(t for t in tokens if 0 <= t < 256).decode(
            "utf-8", errors="replace")

    def apply_chat(self, messages: list[dict]) -> list[int]:
        return self.encode(_chat_fallback_text(messages))


class HfTokenizer:
    # BPE: one string, many tokenizations — a stop string can straddle a
    # token boundary, so text-exact stops need the decoded-text path
    byte_exact = False

    def __init__(self, path: str):
        from transformers import AutoTokenizer
        self._tok = AutoTokenizer.from_pretrained(path)

    @property
    def eos_id(self) -> int:
        return self._tok.eos_token_id if self._tok.eos_token_id is not None else -1

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text)

    def encode_plain(self, text: str) -> list[int]:
        """No BOS/EOS — a stop sequence with a BOS prepended could never
        match a generated tail."""
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, tokens: list[int]) -> str:
        return self._tok.decode(tokens, skip_special_tokens=True)

    def apply_chat(self, messages: list[dict]) -> list[int]:
        """The model's own chat template when the tokenizer ships one;
        otherwise the same minimal role-prefix fallback as ByteTokenizer."""
        if getattr(self._tok, "chat_template", None):
            return self._tok.apply_chat_template(messages,
                                                 add_generation_prompt=True)
        return self._tok.encode(_chat_fallback_text(messages))


def get_tokenizer(spec: Optional[str]):
    """None/"" -> None (ids-only API); "bytes" -> ByteTokenizer;
    anything else -> HF tokenizer directory."""
    if not spec:
        return None
    if spec == "bytes":
        return ByteTokenizer()
    return HfTokenizer(spec)
