"""Sharded training: loss, train step, data, checkpoint/resume.

MaxText-equivalent pretrain loop, TPU-first: the step is one jit over the mesh
(params sharded by the logical rules, batch over data axes), remat is in the
model's scan body, optimizer state inherits param shardings automatically, and
checkpointing is orbax with resume-by-step — the workload half of the
checkpoint/resume story (control-plane half: SURVEY.md §5.4).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Iterator, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.llama import LlamaConfig, LlamaModel, init_params, param_logical_axes
from ..parallel.sharding import logical_sharding, param_shardings

log = logging.getLogger(__name__)


@dataclasses.dataclass
class TrainConfig:
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    weight_decay: float = 0.1
    grad_clip: float = 1.0
    # MaxText-style z-loss coefficient (0 = off; 1e-4 at scale): penalizes
    # log Z^2 of the LM head so logit magnitudes stay bounded in bf16
    z_loss_coef: float = 0.0
    batch_size: int = 8          # GLOBAL batch per optimizer step
    seq_len: int = 512
    steps: int = 100
    # >1: split the global batch into this many microbatches, accumulate
    # grads over a lax.scan, apply ONE optimizer update — fits large global
    # batches in fixed activation memory (activations scale with the
    # microbatch, optimizer cost is unchanged)
    grad_accum_steps: int = 1
    # >0: compute the LM-head loss with the chunked fused cross-entropy
    # (ops/fused_ce.py) streaming the vocab in this many chunks — the
    # (B, S, V) logits tensor never materializes, freeing its HBM for batch.
    # Costs one extra head-matmul pass in backward (recompute), the same
    # trade remat "full" makes for the transformer stack.
    fused_ce_chunks: int = 0
    checkpoint_dir: str = ""
    checkpoint_every: int = 1000
    # async checkpointing for the run() LOOP's periodic saves: stage
    # device->host and let the storage write overlap training (run()
    # waits at its boundary). Direct save() calls always block unless
    # told otherwise. False = loop saves block too.
    async_checkpoint: bool = True
    # elastic resize (ISSUE 6): what happens to the batch when the
    # data-parallel width changes on host loss.
    #   "global"   hold the GLOBAL batch — grad accumulation absorbs the
    #              width change, so the loss trajectory and per-device
    #              activation memory are unchanged (steps get slower);
    #   "per_host" hold the PER-HOST batch — the global batch scales with
    #              the gang (step time holds; the optimizer sees a
    #              different batch size).
    elastic_batch_mode: str = "global"


def cross_entropy_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token NLL. logits (B,S,V) f32/bf16, targets (B,S) int32."""
    ce, _ = _ce_and_zloss(logits, targets, 0.0)
    return ce


def _ce_and_zloss(logits: jax.Array, targets: jax.Array,
                  z_loss_coef: float) -> tuple[jax.Array, jax.Array]:
    """(mean NLL, z-loss term), SHARING one logsumexp reduction: the CE is
    lse - picked_logit (== -log_softmax[target]) and the MaxText-style
    z-loss is coef * mean(lse^2) — no second O(B*S*V) pass over the
    step's largest activation."""
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)                # (B,S)
    picked = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    ce = jnp.mean(lse - picked)
    z = (z_loss_coef * jnp.mean(jnp.square(lse)) if z_loss_coef
         else jnp.float32(0.0))
    return ce, z


def make_optimizer(tc: TrainConfig, trainable_mask=None
                   ) -> optax.GradientTransformation:
    """``trainable_mask``: boolean tree (e.g. models.lora.lora_mask) — frozen
    leaves get ZERO updates and no Adam moments (multi_transform allocates
    state only under the "train" label; that's LoRA's memory win). NOT
    optax.masked(opt, mask): masked passes mask-False updates through
    UNTRANSFORMED, i.e. raw gradients would be added to the frozen weights."""
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, tc.learning_rate, tc.warmup_steps, max(tc.steps, tc.warmup_steps + 1))
    opt = optax.chain(
        optax.clip_by_global_norm(tc.grad_clip),
        optax.adamw(schedule, b1=0.9, b2=0.95, weight_decay=tc.weight_decay),
    )
    if trainable_mask is not None:
        labels = jax.tree_util.tree_map(
            lambda m: "train" if m else "freeze", trainable_mask)
        opt = optax.multi_transform(
            {"train": opt, "freeze": optax.set_to_zero()},
            param_labels=labels)
    return opt


def make_train_step(model: LlamaModel, optimizer: optax.GradientTransformation,
                    donate: bool = True, trainable_mask=None,
                    grad_accum_steps: int = 1, z_loss_coef: float = 0.0,
                    fused_ce_chunks: int = 0):
    """Returns jitted (params, opt_state, batch) -> (params, opt_state, metrics).
    batch: tokens (B, S+1) — inputs are [:, :-1], targets [:, 1:].
    ``trainable_mask``: frozen (False) leaves are stop_gradient'd INSIDE the
    loss, so their backward matmuls are dead code XLA eliminates and no
    gradient HBM is allocated for them — the optimizer-level freeze alone
    would still compute and materialize a full gradient tree every step, and
    grad_norm would be dominated by never-applied gradients.
    ``grad_accum_steps`` > 1 scans over that many microbatches of B/accum
    rows, averaging grads, before the single optimizer update."""

    def loss_and_grads(params, inputs, targets):
        def loss_fn(p):
            if trainable_mask is not None:
                p = jax.tree_util.tree_map(
                    lambda leaf, m: leaf if m else jax.lax.stop_gradient(leaf),
                    p, trainable_mask)
            # optimize CE + router aux (+ z-loss), but report CE separately
            # so MoE/z-loss loss curves stay comparable (exp(loss) = ppl)
            cfg = model.cfg
            head = p.get("tok_embed") if cfg.tie_embeddings else p.get("lm_head")
            # the fused path needs a plain-array head: a LoRA/quant dict leaf
            # (models/lora.py, models/quant.py) falls back to the naive loss
            # — a trace-time (static) decision, no runtime branch
            if fused_ce_chunks and not isinstance(head, dict):
                from ..ops.fused_ce import fused_cross_entropy
                if cfg.n_experts:
                    hidden, aux = model.forward(p, inputs, with_aux=True,
                                                return_hidden=True)
                else:
                    hidden = model.forward(p, inputs, return_hidden=True)
                    aux = jnp.float32(0.0)
                ce, z = fused_cross_entropy(
                    hidden, head, targets, tied=cfg.tie_embeddings,
                    z_loss_coef=z_loss_coef,
                    logit_softcap=cfg.logit_softcap,
                    n_chunks=fused_ce_chunks)
                return ce + aux + z, (ce, aux)
            if cfg.n_experts:
                logits, aux = model.forward(p, inputs, with_aux=True)
            else:
                logits = model.forward(p, inputs)
                aux = jnp.float32(0.0)
            # z-loss keeps logit magnitudes from drifting (bf16 LM heads
            # saturate without it at scale); its logsumexp is shared with
            # the CE computation
            ce, z = _ce_and_zloss(logits, targets, z_loss_coef)
            return ce + aux + z, (ce, aux)

        (_, (ce, aux)), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        return ce, aux, grads

    def _shrink(grads, params):
        """Frozen leaves carry a () placeholder through the scan instead of a
        full zeros buffer — otherwise the accumulator re-materializes the
        full-gradient-tree HBM cost this code exists to avoid."""
        if trainable_mask is None:
            return grads
        return jax.tree_util.tree_map(
            lambda g, m: g if m else jnp.zeros((), g.dtype),
            grads, trainable_mask)

    def _expand(grads, params):
        if trainable_mask is None:
            return grads
        return jax.tree_util.tree_map(
            lambda g, p, m: g if m else jnp.zeros_like(p),
            grads, params, trainable_mask)

    def step(params, opt_state, batch):
        if grad_accum_steps > 1:
            b = batch.shape[0]
            if b % grad_accum_steps:
                raise ValueError(f"batch {b} not divisible by "
                                 f"grad_accum_steps {grad_accum_steps}")
            # STRIDED split (microbatch m = rows m::accum): each microbatch
            # keeps rows from every data-parallel shard, so a batch sharded
            # over the data axes stays balanced — a contiguous reshape would
            # hand each microbatch to a subset of devices and force a
            # reshard every scan iteration
            micro = batch.reshape(b // grad_accum_steps, grad_accum_steps,
                                  batch.shape[1]).swapaxes(0, 1)

            def accum(carry, mb):
                ce, aux, grads = loss_and_grads(params, mb[:, :-1], mb[:, 1:])
                carry = jax.tree_util.tree_map(
                    jnp.add, carry, (_shrink(grads, params), ce, aux))
                return carry, None

            zeros = (_shrink(jax.tree_util.tree_map(
                         lambda p: jnp.zeros(p.shape, jnp.float32), params),
                         params),
                     jnp.float32(0.0), jnp.float32(0.0))
            (grads, ce, aux), _ = jax.lax.scan(accum, zeros, micro)
            scale = 1.0 / grad_accum_steps
            grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
            grads = _expand(grads, params)
            ce, aux = ce * scale, aux * scale
        else:
            ce, aux, grads = loss_and_grads(params, batch[:, :-1], batch[:, 1:])
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grads)
        return params, opt_state, {"loss": ce, "aux_loss": aux,
                                   "grad_norm": gnorm}

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums)


def synthetic_batches(cfg: LlamaConfig, tc: TrainConfig,
                      mesh: Optional[Mesh] = None,
                      seed: int = 0) -> Iterator[jax.Array]:
    """Deterministic synthetic token stream (pretrain-shaped), sharded on the
    data axes when a mesh is given."""
    sharding = None
    if mesh is not None:
        sharding = logical_sharding(mesh, ("batch", None))
    i = seed
    while True:
        key = jax.random.PRNGKey(i)
        batch = jax.random.randint(key, (tc.batch_size, tc.seq_len + 1),
                                   0, cfg.vocab_size, jnp.int32)
        if sharding is not None:
            batch = jax.device_put(batch, sharding)
        yield batch
        i += 1


class Trainer:
    """End-to-end training harness: sharded init, step loop, orbax checkpoints."""

    def __init__(self, cfg: LlamaConfig, tc: TrainConfig,
                 mesh: Optional[Mesh] = None, seed: int = 0,
                 initial_params: Optional[Params] = None,
                 lora: Optional[Any] = None, telemetry: Optional[Any] = None):
        self.cfg = cfg
        self.tc = tc
        self.mesh = mesh
        # ISSUE 5: a workloads.telemetry.TrainingTelemetry — when present the
        # loop device-syncs EVERY step (true per-step wall times are the
        # point; the per-step overhead is the cost of the health signal) and
        # feeds the goodput ledger / step stats / spans. None = the original
        # fire-and-forget loop, unchanged.
        self.telemetry = telemetry
        self._compiled = False  # True once any step has run (bench re-runs)
        self._seed = seed
        self._lora = lora
        self.model = LlamaModel(cfg, mesh)
        if initial_params is not None:
            # host (e.g. HF-converted) tree: commit straight to the target
            # shardings — never a random init that would be thrown away, and
            # never a full copy on one device first
            if mesh is not None:
                axes = param_logical_axes(cfg)
                self.params = jax.tree_util.tree_map(
                    lambda p, a: jax.device_put(p, logical_sharding(mesh, a)),
                    initial_params, axes)
            else:
                self.params = jax.tree_util.tree_map(jnp.asarray,
                                                     initial_params)
        else:
            self.params = init_params(cfg, jax.random.PRNGKey(seed), mesh)
        mask = None
        if lora is not None:
            from ..models.lora import apply_lora, lora_mask
            self.params = apply_lora(cfg, self.params, lora,
                                     jax.random.PRNGKey(seed + 1), mesh)
            mask = lora_mask(self.params)
        self.optimizer = make_optimizer(tc, trainable_mask=mask)
        # optax state mirrors the (already-sharded) params, so it inherits
        # their shardings — no separate placement pass needed
        self.opt_state = self.optimizer.init(self.params)
        self.step_fn = make_train_step(self.model, self.optimizer,
                                       trainable_mask=mask,
                                       grad_accum_steps=tc.grad_accum_steps,
                                       z_loss_coef=tc.z_loss_coef,
                                       fused_ce_chunks=tc.fused_ce_chunks)
        self.step = 0
        self._eval_fn = None
        self._ckpt = None
        if tc.checkpoint_dir:
            import orbax.checkpoint as ocp
            self._ckpt = ocp.CheckpointManager(
                tc.checkpoint_dir,
                options=ocp.CheckpointManagerOptions(max_to_keep=3))

    # -- checkpoint / resume ---------------------------------------------------

    def save(self, block: bool = True):
        """Checkpoint params + optimizer state. DIRECT calls block until
        durable (a caller that saves then exits or restores must never
        race the write). The run() loop's periodic saves pass
        ``block=False`` (TrainConfig.async_checkpoint): orbax stages
        device->host and the storage write overlaps the next training
        steps — at real model sizes that write is seconds-to-minutes the
        accelerators would otherwise idle (MaxText-style) — and run()
        waits at its boundary so nothing is lost."""
        if self._ckpt is None:
            return
        import orbax.checkpoint as ocp
        import contextlib
        # block=False only STAGES the write: the telemetry exposure marker
        # must not reset until wait_pending() proves it durable — a
        # preemption mid-background-write loses those steps
        span = (self.telemetry.checkpoint("save", step=self.step,
                                          durable=block)
                if self.telemetry is not None else contextlib.nullcontext())
        with span:
            self._ckpt.save(self.step, args=ocp.args.StandardSave(
                {"params": self.params, "opt_state": self.opt_state}))
            if block:
                self._ckpt.wait_until_finished()
        if block:
            log.info("checkpoint saved at step %d", self.step)
        else:
            log.info("checkpoint staged at step %d (write in background)",
                     self.step)

    def wait_pending(self):
        """Block until any in-flight async checkpoint write is durable."""
        if self._ckpt is not None:
            self._ckpt.wait_until_finished()
            if self.telemetry is not None:
                # any staged save is now durable: the telemetry exposure
                # baseline moves to its staging point
                self.telemetry.checkpoint_durable()

    def restore(self) -> bool:
        # an in-flight async write of the newest step must land before
        # latest_step()/restore read it
        self.wait_pending()
        if self._ckpt is None or self._ckpt.latest_step() is None:
            return False
        if self.telemetry is not None:
            with self.telemetry.checkpoint("restore",
                                           step=self._ckpt.latest_step()):
                return self._restore_inner()
        return self._restore_inner()

    def _restore_inner(self) -> bool:
        import orbax.checkpoint as ocp
        target = {"params": self.params, "opt_state": self.opt_state}

        # Restore onto an ABSTRACT target with explicit shardings. Passing
        # the concrete values lets orbax commit leaves to whatever device
        # they currently sit on — and optax's eager init() leaves its scalar
        # counters on the default device while the params are mesh-sharded,
        # so the first post-restore step_fn dies on "incompatible devices"
        # (restored arrays are committed; fresh ones were movable). Found by
        # the preemption-resume path, which is exactly a sharded restore.
        # Mesh runs: keep NamedShardings, replicate everything else.
        def _restore_spec(x):
            s = x.sharding
            if self.mesh is not None and not isinstance(
                    s, jax.sharding.NamedSharding):
                s = jax.sharding.NamedSharding(self.mesh,
                                               jax.sharding.PartitionSpec())
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)

        restored = self._ckpt.restore(
            self._ckpt.latest_step(),
            args=ocp.args.StandardRestore(jax.tree.map(_restore_spec, target)))
        self.params = restored["params"]
        self.opt_state = restored["opt_state"]
        self.step = self._ckpt.latest_step()
        log.info("resumed from checkpoint step %d", self.step)
        return True

    # -- elastic resize (ISSUE 6) ----------------------------------------------

    def resize(self, mesh: Mesh) -> bool:
        """Continue training on a DIFFERENT mesh: rebuild the model/step over
        the surviving (or restored) devices, rescale the batch per
        ``tc.elastic_batch_mode``, and reshard params + optimizer state from
        the latest durable orbax checkpoint under the new NamedShardings —
        the same StandardRestore-with-shardings seam preemption recovery
        uses, so a shrink is "restore onto fewer devices", not a bespoke
        gather/scatter. Returns True when a checkpoint was restored; False
        means none exists and training restarts from a fresh init at the
        new width (step 0 — nothing durable to continue from)."""
        import dataclasses as _dc

        from ..parallel.mesh import dp_width
        old_dp = dp_width(self.mesh) if self.mesh is not None else 1
        new_dp = dp_width(mesh)
        tc = self.tc
        if tc.elastic_batch_mode == "per_host":
            # hold the per-DP-shard batch: global batch scales with the gang
            batch = max(1, (tc.batch_size * new_dp) // max(1, old_dp))
            accum = tc.grad_accum_steps
        else:  # "global": hold the global batch, let grad accum absorb it
            batch = tc.batch_size
            accum = max(1, round(tc.grad_accum_steps * old_dp / new_dp))
        multiple = new_dp * accum
        rounded = ((batch + multiple - 1) // multiple) * multiple
        if rounded != batch:
            log.info("resize: batch %d -> %d (must divide dp %d x accum %d)",
                     batch, rounded, new_dp, accum)
        # a pending async save must land BEFORE the old mesh's arrays are
        # dropped — orbax is still staging from them
        self.wait_pending()
        # drop the dead width's executables and traces: every program must
        # re-trace for the new mesh anyway (a stale jit cache entry keyed on
        # the old shardings would silently recommit arrays to dead devices),
        # and freeing them bounds live-executable accumulation across
        # repeated resizes (tests/conftest.py pins an XLA:CPU bug there)
        jax.clear_caches()
        self.mesh = mesh
        self.tc = _dc.replace(tc, batch_size=rounded, grad_accum_steps=accum)
        self.model = LlamaModel(self.cfg, mesh)
        self.params = init_params(self.cfg, jax.random.PRNGKey(self._seed),
                                  mesh)
        mask = None
        if self._lora is not None:
            from ..models.lora import apply_lora, lora_mask
            self.params = apply_lora(self.cfg, self.params, self._lora,
                                     jax.random.PRNGKey(self._seed + 1), mesh)
            mask = lora_mask(self.params)
        self.opt_state = self.optimizer.init(self.params)
        self.step_fn = make_train_step(self.model, self.optimizer,
                                       trainable_mask=mask,
                                       grad_accum_steps=accum,
                                       z_loss_coef=self.tc.z_loss_coef,
                                       fused_ce_chunks=self.tc.fused_ce_chunks)
        self._eval_fn = None
        self._compiled = False  # the new width compiles fresh programs
        restored = self.restore()
        if not restored:
            self.step = 0
            log.warning("resize to dp=%d found no checkpoint in %r — "
                        "training restarts at step 0", new_dp,
                        self.tc.checkpoint_dir)
        else:
            log.info("resized dp %d -> %d: resumed from checkpoint step %d "
                     "(batch %d, grad_accum %d)", old_dp, new_dp, self.step,
                     self.tc.batch_size, self.tc.grad_accum_steps)
        if self.telemetry is not None:
            # throughput math follows the (possibly rescaled) global batch
            self.telemetry.stats.tokens_per_step = (self.tc.batch_size
                                                    * self.tc.seq_len)
        return restored

    # -- eval ------------------------------------------------------------------

    def evaluate(self, batches: Optional[Iterator] = None,
                 steps: int = 10) -> dict:
        """Forward-only held-out evaluation: mean next-token NLL and
        perplexity over ``steps`` batches (MaxText's eval loop shape).
        Default batches use the MICRObatch size — a run whose global batch
        only fits via grad accumulation must not OOM in its final eval."""
        if batches is None:
            etc = dataclasses.replace(
                self.tc,
                batch_size=max(1, self.tc.batch_size
                               // max(1, self.tc.grad_accum_steps)))
            batches = synthetic_batches(self.cfg, etc, self.mesh,
                                        seed=10_000_019)
        if self._eval_fn is None:
            def eval_loss(params, batch):
                cfg = self.model.cfg
                head = (params.get("tok_embed") if cfg.tie_embeddings
                        else params.get("lm_head"))
                # same fused/naive split as the train loss: a 128k-vocab
                # model that only trains via fused CE must not OOM in its
                # final eval by materializing eval logits
                if self.tc.fused_ce_chunks and not isinstance(head, dict):
                    from ..ops.fused_ce import fused_cross_entropy
                    hidden = self.model.forward(params, batch[:, :-1],
                                                return_hidden=True)
                    ce, _ = fused_cross_entropy(
                        hidden, head, batch[:, 1:],
                        tied=cfg.tie_embeddings,
                        logit_softcap=cfg.logit_softcap,
                        n_chunks=self.tc.fused_ce_chunks)
                    return ce
                logits = self.model.forward(params, batch[:, :-1])
                return cross_entropy_loss(logits, batch[:, 1:])
            self._eval_fn = jax.jit(eval_loss)
        total = 0.0
        for _ in range(steps):
            total += float(self._eval_fn(self.params, next(batches)))
        nll = total / max(steps, 1)
        return {"eval_loss": nll, "eval_ppl": float(jnp.exp(jnp.float32(nll))),
                "eval_steps": steps}

    # -- loop ------------------------------------------------------------------

    def run(self, steps: Optional[int] = None,
            batches: Optional[Iterator] = None,
            resize_signal: Optional[Any] = None) -> dict:
        """``resize_signal``: optional zero-arg callable polled after every
        step (the elastic host-loss trigger — a watchdog stall flag, a
        heartbeat timeout, a test hook). A truthy return stops the loop
        cleanly at the step boundary and is surfaced as
        ``out["resize_request"]``; the caller resizes the mesh
        (``Trainer.resize``) and calls run() again for the remaining steps."""
        steps = steps or self.tc.steps
        batches = batches or synthetic_batches(self.cfg, self.tc, self.mesh)
        metrics: dict = {}
        tel = self.telemetry
        if tel is not None:
            tel.run_started(self.step, compiled=self._compiled)
        t0 = time.perf_counter()
        tokens_per_batch = self.tc.batch_size * self.tc.seq_len
        first_step_s = None
        t_step = t0
        done = 0
        resize_request = None
        for _ in range(steps):
            batch = next(batches)
            self.params, self.opt_state, metrics = self.step_fn(
                self.params, self.opt_state, batch)
            if first_step_s is None:
                jax.block_until_ready(metrics["loss"])
                first_step_s = time.perf_counter() - t0
            self.step += 1
            done += 1
            if tel is not None:
                # sync EVERY step: the recorded step time must be device
                # time, not dispatch time (the telemetry contract)
                jax.block_until_ready(metrics["loss"])
                now = time.perf_counter()
                tel.record_step(self.step, now - t_step,
                                loss=float(metrics["loss"]))
                t_step = now
            if self.tc.checkpoint_dir and self.step % self.tc.checkpoint_every == 0:
                self.save(block=not self.tc.async_checkpoint)
                t_step = time.perf_counter()  # save time is not step time
            if resize_signal is not None:
                resize_request = resize_signal()
                if resize_request:
                    log.warning("host-loss signal at step %d — stopping the "
                                "loop for an elastic resize: %s", self.step,
                                resize_request)
                    break
        jax.block_until_ready(metrics["loss"])
        self._compiled = True
        wall = time.perf_counter() - t0
        # async checkpoint boundary: the loop's staged writes must be
        # durable before the run reports done (wall above excludes this
        # wait on purpose — overlapping it with training IS the feature)
        self.wait_pending()
        out = {
            "steps": done,
            "final_loss": float(metrics["loss"]),
            "grad_norm": float(metrics["grad_norm"]),
            "wall_s": wall,
            "first_step_s": first_step_s,
            "tokens_per_s": tokens_per_batch * done / wall,
        }
        if resize_request:
            out["resize_request"] = resize_request
        if tel is not None:
            out.update(tel.run_finished({"steps": done}))
        return out
