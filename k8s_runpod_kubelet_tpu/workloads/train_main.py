"""Gang-scheduled pretrain workload (BASELINE.json configs 3-4, MaxText analog).

The pod command for multi-host slices. On every worker:
  1. jax.distributed forms from the kubelet-injected env (gang/env.py),
  2. a mesh is built over the full slice (all hosts' chips),
  3. the sharded train loop runs; worker 0 logs throughput + a JSON summary.

Run: python -m k8s_runpod_kubelet_tpu.workloads.train_main \
        --model llama3-8b --steps 100 --tensor 4 [--fsdp -1] [--seq 1]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import time

from ..parallel.distributed import initialize_from_env

log = logging.getLogger("train-main")


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    from ..models import MODEL_CONFIGS
    p.add_argument("--model", default="llama3-8b",
                   choices=list(MODEL_CONFIGS))
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--z-loss", type=float, default=0.0,
                   help="z-loss coefficient (MaxText uses 1e-4 at scale): "
                        "keeps LM-head logit magnitudes bounded in bf16")
    p.add_argument("--tensor", type=int, default=1)
    p.add_argument("--seq", type=int, default=1, help="sequence-parallel degree")
    p.add_argument("--stage", type=int, default=1, help="pipeline-parallel degree")
    p.add_argument("--microbatches", type=int, default=0,
                   help="pipeline microbatches (0 = one per stage)")
    p.add_argument("--fsdp", type=int, default=0,
                   help="0 or -1 = auto: all non-tp/sp/pp devices")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="microbatches per optimizer step (grad accumulation)")
    p.add_argument("--fused-ce-chunks", type=int, default=0,
                   help="stream the LM-head loss over this many vocab chunks "
                        "(0 = materialize logits); frees the (B,S,V) logits "
                        "HBM for batch at one extra head matmul in backward")
    p.add_argument("--eval-steps", type=int, default=0,
                   help="run a held-out eval of this many batches at the end "
                        "(and report eval_loss/eval_ppl)")
    p.add_argument("--lora-rank", type=int, default=0,
                   help="enable LoRA fine-tuning at this rank (0 = full "
                        "fine-tune); base weights freeze, only adapters train")
    p.add_argument("--lora-alpha", type=float, default=16.0)
    p.add_argument("--lora-targets", default="wq,wv",
                   help="comma list of projections to adapt "
                        "(wq,wk,wv,wo,w_gate,w_up,w_down)")
    p.add_argument("--export-adapter", default="",
                   help="after a --lora-rank run, write the trained adapter "
                        "alone to this .npz (a few MB) — POST it to the "
                        "serving /adapters endpoint for multi-LoRA serving")
    p.add_argument("--hf-checkpoint", default="",
                   help="initialize weights from a HuggingFace model "
                        "directory (fine-tune); an orbax checkpoint in "
                        "--checkpoint-dir still wins on resume")
    p.add_argument("--checkpoint-dir", default="")
    p.add_argument("--checkpoint-every", type=int, default=500)
    p.add_argument("--data", default="",
                   help="pre-tokenized int32 corpus file (empty = synthetic); "
                        "read through the native loader, sharded per process")
    p.add_argument("--data-threads", type=int, default=2)
    p.add_argument("--profile-dir", default="",
                   help="capture a JAX/XLA profiler trace of a few post-warmup "
                        "steps into this directory (TensorBoard-readable)")
    p.add_argument("--profiler-port", type=int, default=0,
                   help="start the on-demand jax.profiler server on this port "
                        "(0 = off); lets an operator capture traces from a "
                        "running worker without restarting it")
    # training telemetry (ISSUE 5): goodput ledger + step/MFU stats +
    # straggler watchdog. Defaults come from the kubelet-injected env
    # (gang/env.py coordination vars) so the pod spec needs no flags.
    p.add_argument("--telemetry-port", type=int,
                   default=int(os.environ.get("TPU_TELEMETRY_PORT", "0") or 0),
                   help="worker-0 serves /metrics + /debug/train + POST "
                        "/heartbeat on this port (0 = off); peers POST "
                        "their step heartbeats here")
    p.add_argument("--straggler-factor", type=float,
                   default=float(os.environ.get("TPU_STRAGGLER_FACTOR",
                                                "3.0") or 3.0),
                   help="flag a host whose mean step time exceeds this "
                        "multiple of the across-host median")
    p.add_argument("--stall-timeout-s", type=float,
                   default=float(os.environ.get("TPU_STALL_TIMEOUT_S",
                                                "120") or 120),
                   help="flag a host whose step counter stops advancing "
                        "for this many seconds")
    p.add_argument("--telemetry-every", type=int, default=1,
                   help="emit the TPU_TELEMETRY state line every N steps "
                        "(heartbeats go every step regardless)")
    p.add_argument("--trace-export",
                   default=os.environ.get("TPU_TRACE_EXPORT_PATH", ""),
                   help="append training.* spans to this JSONL file; render "
                        "with tools/goodput_summary.py / trace_summary.py")
    p.add_argument("--elastic-batch-mode",
                   default=os.environ.get("TPU_ELASTIC_BATCH_MODE",
                                          "global") or "global",
                   choices=("global", "per_host"),
                   help="elastic gang training (ISSUE 6): when the kubelet "
                        "resizes the gang on host loss, either hold the "
                        "GLOBAL batch via grad accumulation (loss "
                        "trajectory unchanged, steps slower) or hold the "
                        "PER-HOST batch (global batch scales with the gang)")
    args = p.parse_args(argv)
    if args.export_adapter and args.lora_rank <= 0:
        # fail at arg time, not after a multi-hour run
        p.error("--export-adapter needs --lora-rank")
    logging.basicConfig(level=logging.INFO)

    # checkpoint-aware preemption recovery (ISSUE 3): the kubelet injects
    # TPU_RESTART_ATTEMPT (>0 after a preemption requeue) and, when the pod
    # carries the tpu.dev/checkpoint-dir annotation, TPU_CHECKPOINT_DIR —
    # so a requeued gang resumes from its latest orbax step instead of
    # step 0 without the pod spec having to thread flags through.
    restart_attempt = int(os.environ.get("TPU_RESTART_ATTEMPT", "0") or 0)
    if not args.checkpoint_dir and os.environ.get("TPU_CHECKPOINT_DIR"):
        args.checkpoint_dir = os.environ["TPU_CHECKPOINT_DIR"]
        log.info("checkpoint dir from TPU_CHECKPOINT_DIR: %s",
                 args.checkpoint_dir)
    if restart_attempt:
        log.info("restart attempt %d (post-preemption relaunch)",
                 restart_attempt)

    # 1. the gang forms (no-op single process)
    pe = initialize_from_env()

    # elastic gang resize (ISSUE 6): on a resize relaunch the kubelet has
    # already renumbered JAX_NUM_PROCESSES/JAX_PROCESS_ID over the surviving
    # hosts and injected TPU_ELASTIC_RESIZE + TPU_GANG_FULL_HOSTS — the gang
    # simply forms at the surviving width and this block (a) logs the marker
    # line the operator greps, (b) rescales the batch per the chosen mode.
    from ..parallel.distributed import resize_env_summary
    re_env = resize_env_summary(pe)
    if re_env.is_resized and pe.process_id == 0:
        log.info("elastic resize %d: continuing at %d/%d hosts",
                 re_env.resize_count, pe.num_processes, re_env.full_hosts)
    if re_env.shrunk(pe):
        scale = re_env.full_hosts / max(1, pe.num_processes)
        if args.elastic_batch_mode == "global":
            # hold the global batch: grad accumulation absorbs the lost
            # hosts, so per-device activation memory and the loss
            # trajectory are unchanged (steps get slower)
            args.grad_accum = max(1, round(max(1, args.grad_accum) * scale))
        else:  # per_host: the global batch shrinks with the gang
            args.batch = max(1, round(args.batch / scale))
        if pe.process_id == 0:
            log.info("elastic resize: batch_mode=%s -> global batch %d, "
                     "grad_accum %d", args.elastic_batch_mode, args.batch,
                     args.grad_accum)

    import jax
    if args.profiler_port:
        jax.profiler.start_server(args.profiler_port)
        log.info("jax profiler server on :%d", args.profiler_port)
    from ..parallel import MeshConfig, make_mesh
    from ..workloads.train import TrainConfig, Trainer

    n = jax.device_count()
    cfg = MODEL_CONFIGS[args.model]()
    if args.stage > 1:
        if cfg.n_layers % args.stage:
            raise SystemExit(f"--stage {args.stage} must divide "
                             f"n_layers={cfg.n_layers}")
        if args.seq > 1:
            raise SystemExit("--stage does not compose with --seq: the "
                             "pipelined forward cannot ring-shard the "
                             "sequence; give those devices to --fsdp/--tensor")
        cfg = dataclasses.replace(
            cfg, pipeline_microbatches=args.microbatches or None)
    fsdp = args.fsdp if args.fsdp > 0 else max(
        1, n // (args.tensor * args.seq * args.stage))
    mesh = make_mesh(MeshConfig(data=-1, fsdp=fsdp, seq=args.seq,
                                stage=args.stage, tensor=args.tensor))
    if pe.process_id == 0:
        log.info("model=%s params=%.2fB devices=%d mesh=%s slice=%s",
                 cfg.name, cfg.param_count / 1e9, n, dict(mesh.shape),
                 pe.accelerator_type or "local")

    # global batch must divide evenly over the data axes (and, when
    # pipelining, over the microbatch count)
    dp_total = mesh.shape["data"] * mesh.shape["fsdp"]
    if args.stage > 1:
        dp_total *= (args.microbatches or args.stage)
    multiple = dp_total * max(1, args.grad_accum)
    batch = ((args.batch + multiple - 1) // multiple) * multiple
    if batch != args.batch:
        log.info("batch %d -> %d (must divide data*fsdp*microbatches"
                 "*grad_accum=%d)", args.batch, batch, multiple)
    tc = TrainConfig(learning_rate=args.lr, batch_size=batch,
                     seq_len=args.seq_len, steps=args.steps,
                     z_loss_coef=args.z_loss,
                     grad_accum_steps=args.grad_accum,
                     fused_ce_chunks=args.fused_ce_chunks,
                     checkpoint_dir=args.checkpoint_dir,
                     checkpoint_every=args.checkpoint_every,
                     elastic_batch_mode=args.elastic_batch_mode)
    initial = None
    if args.hf_checkpoint:
        from ..models import load_hf
        initial = load_hf(cfg, args.hf_checkpoint)  # host tree; Trainer shards
        log.info("initializing from HF checkpoint %s", args.hf_checkpoint)
    lora = None
    if args.lora_rank > 0:
        from ..models import LoraConfig
        lora = LoraConfig(rank=args.lora_rank, alpha=args.lora_alpha,
                          targets=tuple(t for t in
                                        args.lora_targets.split(",") if t))

    # -- training telemetry (ISSUE 5) ------------------------------------------
    # Every worker keeps a ledger + step stats and prints the heartbeat /
    # TPU_TELEMETRY protocol lines to stderr (docker logs carry them — the
    # kubelet scrapes worker-0's). Worker-0 additionally aggregates peers'
    # heartbeats (POST /heartbeat) into the straggler watchdog and serves
    # /metrics + /debug/train.
    import sys as _sys

    from ..health import HealthServer
    from ..metrics import Metrics
    from ..tracing import Tracer
    from .telemetry import (HeartbeatPoster, TrainingTelemetry, state_path_for)

    tel_metrics = Metrics()
    tracer = Tracer(export_path=args.trace_export)
    poster = None
    tel_address = os.environ.get("TPU_TELEMETRY_ADDRESS", "")
    if pe.process_id != 0 and args.telemetry_port and tel_address:
        poster = HeartbeatPoster(tel_address)

    def emit_line(line: str, _poster=poster):
        print(line, file=_sys.stderr, flush=True)
        if _poster is not None and line.startswith("TPU_STEP_HEARTBEAT"):
            _poster(line)

    tel = TrainingTelemetry(
        tokens_per_step=batch * args.seq_len,
        model_params=cfg.param_count, n_chips=n,
        accelerator_type=pe.accelerator_type
        or os.environ.get("TPU_ACCELERATOR_TYPE", ""),
        num_hosts=pe.num_processes, host_id=pe.process_id,
        metrics=tel_metrics, tracer=tracer,
        straggler_factor=args.straggler_factor,
        stall_timeout_s=args.stall_timeout_s,
        attempt=restart_attempt,
        resize_attempt=re_env.resize_count,
        dp_width=mesh.shape["data"] * mesh.shape["fsdp"],
        state_path=state_path_for(args.checkpoint_dir),
        telemetry_every=args.telemetry_every,
        emit_line=emit_line)
    if restart_attempt and tel.restart_lost_s > 0 and pe.process_id == 0:
        log.info("goodput ledger: %.1fs charged to restart_lost "
                 "(attempt %d, prior step %d)",
                 tel.restart_lost_s, restart_attempt, tel.resumed_from_step)
    if re_env.resize_count and tel.resize_lost_s > 0 and pe.process_id == 0:
        log.info("goodput ledger: %.1fs charged to resize "
                 "(resize %d, prior step %d)",
                 tel.resize_lost_s, re_env.resize_count, tel.resumed_from_step)
    tel_server = None
    if pe.process_id == 0 and args.telemetry_port:
        tel_server = HealthServer(f":{args.telemetry_port}",
                                  metrics=tel_metrics, tracer=tracer,
                                  train_status=tel.snapshot,
                                  heartbeat_sink=tel.ingest_heartbeat).start()
        log.info("telemetry server on :%d (/metrics /debug/train "
                 "POST /heartbeat)", tel_server.port)
    sweeper_stop = None
    if pe.process_id == 0 and pe.num_processes > 1:
        # the straggler sweep must fire even while worker-0 itself is wedged
        # in a collective (record_step stops being called) — a tiny thread,
        # real clock, worker-0 only
        import threading as _threading
        sweeper_stop = _threading.Event()

        def _sweep():
            interval = max(0.5, args.stall_timeout_s / 4.0)
            while not sweeper_stop.wait(interval):
                tel.check_stragglers()

        _threading.Thread(target=_sweep, name="straggler-sweep",
                          daemon=True).start()

    trainer = Trainer(cfg, tc, mesh=mesh, initial_params=initial, lora=lora,
                      telemetry=tel)
    if lora is not None and pe.process_id == 0:
        from ..models import lora_param_count
        log.info("LoRA r=%d: %.2fM trainable of %.2fB total",
                 args.lora_rank, lora_param_count(trainer.params) / 1e6,
                 cfg.param_count / 1e9)
    if args.checkpoint_dir:
        # resume-from-preemption path (wins over --hf-checkpoint). restore()
        # logs "resumed from checkpoint step N" — the marker the kubelet's
        # RecoveredFromPreemption event parses out of worker-0 logs.
        restored = trainer.restore()
        if restart_attempt and pe.process_id == 0:
            if restored:
                log.info("preemption recovery: attempt %d resumes at step %d",
                         restart_attempt, trainer.step)
            else:
                log.warning("preemption recovery: attempt %d found NO "
                            "checkpoint in %s — training restarts at step 0",
                            restart_attempt, args.checkpoint_dir)
    batches = None
    loader = None
    if args.data:
        from ..data import device_batches, make_loader
        if batch % pe.num_processes:
            raise SystemExit(f"global batch {batch} must divide over "
                             f"{pe.num_processes} processes")
        # per-process local rows; device_batches assembles the global array.
        # start_batch seeks past data a resumed run already consumed.
        loader = make_loader(args.data, seq_len=args.seq_len,
                             batch_size=batch // pe.num_processes,
                             vocab_size=cfg.vocab_size,
                             threads=args.data_threads,
                             shard_id=pe.process_id,
                             num_shards=pe.num_processes,
                             start_batch=trainer.step)
        batches = device_batches(loader, mesh)
    try:
        if args.profile_dir and args.steps > 4:
            # §5.1: profiler hooks on workers — capture a few POST-compile
            # steps so the trace shows steady-state device time, not tracing
            trainer.run(steps=2, batches=batches)
            with jax.profiler.trace(args.profile_dir):
                out = trainer.run(steps=3, batches=batches)
            log.info("profiler trace written to %s", args.profile_dir)
            if args.steps > 5:  # steps=0 would mean "tc.steps more" to run()
                out = trainer.run(steps=args.steps - 5, batches=batches)
        else:
            out = trainer.run(steps=args.steps, batches=batches)
    finally:
        if loader is not None:
            loader.close()
    if args.checkpoint_dir:
        trainer.save()  # blocks: final checkpoint is durable before exit
    if args.export_adapter and pe.process_id == 0:
        # adapters are fully replicated across the mesh (apply_lora), so
        # process 0 holds every value even on multi-host runs
        from ..models.lora import save_adapter
        written = save_adapter(args.export_adapter, trainer.params)
        log.info("adapter written to %s", written)

    if args.eval_steps > 0:
        out.update(trainer.evaluate(steps=args.eval_steps))
    if sweeper_stop is not None:
        sweeper_stop.set()
    if poster is not None:
        poster.close()
    if tel_server is not None:
        tel_server.stop()
    tracer.close()  # flush the JSONL span export before the summary prints
    if pe.process_id == 0:
        out.update({"workload": "pretrain", "model": cfg.name,
                    "devices": n, "mesh": {k: v for k, v in mesh.shape.items()},
                    "tokens_per_s_per_chip": round(out["tokens_per_s"] / n, 1)})
        print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
