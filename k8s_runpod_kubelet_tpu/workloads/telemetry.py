"""Training telemetry: goodput ledger, step/MFU stats, straggler watchdog.

The training path was the last major subsystem with zero observability:
``Trainer.run()`` computed loss/grad_norm and nothing else, and the kubelet
only learned a training pod was *alive*, not whether it was making progress.
This module is the workload half of ISSUE 5 — step-time/MFU is the canonical
TPU training health signal ("Exploring the limits of Concurrency in ML
Training on Google TPUs"), and progress/straggler signals are exactly what
the scheduler layer (kubelet + fleet) needs on preemption-heavy capacity
(Gavel's heterogeneity-aware policies).

Design constraints, in order:
- stdlib only (runs inside the workload container; must not drag jax in —
  it is imported by the kubelet-side scrape and by tools);
- injected-clock everywhere: the ledger/watchdog take a ``clock`` callable,
  so every invariant here is provable on a FakeClock with zero real sleeps;
- the GoodputLedger's buckets are EXCLUSIVE (exactly one is open at any
  instant — ``switch`` closes the open bucket and opens the next) and
  therefore sum to wall clock by construction; restart cost carried in from
  a prior attempt (``charge``) extends the wall total so the invariant
  survives preemption attribution;
- one line protocol shared by every consumer: workers print
  ``TPU_STEP_HEARTBEAT ...`` / ``TPU_TELEMETRY {json}`` lines that worker-0
  aggregates (POST /heartbeat) and the kubelet scrapes out of worker-0 logs
  through the same ``GangExecutor`` surface the preemption-recovery event
  already uses — so the fake cloud path exercises the real parse.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from typing import Callable, Optional

log = logging.getLogger(__name__)

# -- peak-FLOPs table ----------------------------------------------------------

# The roofline table moved to the shared generations module (ISSUE 19) —
# bench.py, cloud/types.py and fleet/scheduler.py read the SAME rows; the
# names below stay importable from here for the training-side MFU math.
from ..generations import (PEAK_TFLOPS_BF16, generation_of,  # noqa: F401
                           peak_tflops_per_chip)


# -- the line protocol ---------------------------------------------------------

HEARTBEAT_MARKER = "TPU_STEP_HEARTBEAT"
TELEMETRY_MARKER = "TPU_TELEMETRY"
# the kubelet-side scrape pattern (GangExecutor.last_in_logs): the LAST
# telemetry line in worker-0's recent logs is the pod's current state
TELEMETRY_PATTERN = r"TPU_TELEMETRY (\{.*\})"

_HEARTBEAT_RE = re.compile(
    r"TPU_STEP_HEARTBEAT host=(\d+) step=(\d+) step_time_s=([0-9.eE+-]+)")


def format_heartbeat(host: int, step: int, step_time_s: float) -> str:
    """One worker's per-step progress beat (printed to its own log AND
    POSTed to worker-0's /heartbeat when a telemetry port is wired)."""
    return (f"{HEARTBEAT_MARKER} host={host} step={step} "
            f"step_time_s={step_time_s:.6f}")


def parse_heartbeat(line: str) -> Optional[tuple[int, int, float]]:
    """(host, step, step_time_s) from a heartbeat line, else None."""
    m = _HEARTBEAT_RE.search(line)
    if not m:
        return None
    return int(m.group(1)), int(m.group(2)), float(m.group(3))


def format_telemetry(payload: dict) -> str:
    """Worker-0's aggregated state line (the kubelet scrape target)."""
    return f"{TELEMETRY_MARKER} {json.dumps(payload, sort_keys=True)}"


def parse_telemetry(text: str) -> Optional[dict]:
    """The LAST well-formed telemetry payload in a log body, else None."""
    out = None
    for m in re.finditer(TELEMETRY_PATTERN, text):
        try:
            out = json.loads(m.group(1))
        except json.JSONDecodeError:
            continue
    return out if isinstance(out, dict) else None


# -- goodput ledger ------------------------------------------------------------

class GoodputLedger:
    """Wall-clock accounting into EXCLUSIVE buckets that sum to wall time.

    Exactly one bucket is open at any instant: ``switch(b)`` closes the open
    bucket (crediting it the elapsed interval) and opens ``b``. Because the
    intervals are consecutive measurements of one clock, the bucket totals
    telescope to ``now - start`` — the sum-to-wall-clock invariant is
    structural, not bookkeeping, and the tier-1 test asserts it across a
    simulated preemption/restart cycle.

    Preemption attribution: work a prior attempt did after its last durable
    checkpoint is gone, and so is the downtime between its death and this
    attempt's start. ``charge("restart_lost", s)`` credits that externally-
    known cost; it extends the wall total by the same amount so the
    invariant still holds (lost time IS wall time the run paid for).
    """

    BUCKETS = ("productive", "compile", "checkpoint_save",
               "checkpoint_restore", "restart_lost", "resize", "stalled",
               "idle")

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 start_bucket: str = "idle"):
        if start_bucket not in self.BUCKETS:
            raise ValueError(f"unknown bucket {start_bucket!r}")
        self._clock = clock
        self._lock = threading.Lock()
        self._start = clock()
        self._acc = {b: 0.0 for b in self.BUCKETS}
        self._external = 0.0
        self._open = start_bucket
        self._opened_at = self._start

    @property
    def open_bucket(self) -> str:
        with self._lock:  # _open flips under the lock in switch()
            return self._open

    def switch(self, bucket: str) -> float:
        """Close the open bucket into its accumulator, open ``bucket``.
        Returns the just-closed interval's duration (seconds)."""
        if bucket not in self.BUCKETS:
            raise ValueError(f"unknown bucket {bucket!r}")
        with self._lock:
            now = self._clock()
            closed = now - self._opened_at
            self._acc[self._open] += closed
            self._open = bucket
            self._opened_at = now
            return closed

    def spend(self, bucket: str) -> "_Spend":
        """Context manager: open ``bucket`` on entry, restore the previously
        open bucket on exit (nesting-safe). The yielded object's
        ``.duration_s`` is the interval spent inside."""
        return _Spend(self, bucket)

    def charge(self, bucket: str, seconds: float):
        """Credit an externally-measured cost (a PRIOR attempt's lost work +
        downtime). Extends the wall total so buckets still sum to wall."""
        if bucket not in self.BUCKETS:
            raise ValueError(f"unknown bucket {bucket!r}")
        if seconds < 0:
            raise ValueError("charge must be >= 0")
        with self._lock:
            self._acc[bucket] += seconds
            self._external += seconds

    def total(self, bucket: str) -> float:
        """Bucket total including its open interval, if it is the open one."""
        with self._lock:
            t = self._acc[bucket]
            if bucket == self._open:
                t += self._clock() - self._opened_at
            return t

    def wall_s(self) -> float:
        with self._lock:
            return (self._clock() - self._start) + self._external

    @property
    def goodput(self) -> float:
        """productive / wall (0 when no wall time has passed)."""
        wall = self.wall_s()
        return self.total("productive") / wall if wall > 0 else 0.0

    def snapshot(self) -> dict:
        """Point-in-time view: per-bucket seconds (open interval included),
        wall_s, goodput, and lost_s per non-productive cause."""
        with self._lock:
            now = self._clock()
            acc = dict(self._acc)
            acc[self._open] += now - self._opened_at
            wall = (now - self._start) + self._external
        goodput = acc["productive"] / wall if wall > 0 else 0.0
        lost = {b: round(v, 6) for b, v in acc.items()
                if b != "productive" and v > 0}
        return {"buckets": {b: round(v, 6) for b, v in acc.items()},
                "wall_s": round(wall, 6), "goodput": round(goodput, 6),
                "lost_s": lost}


class _Spend:
    def __init__(self, ledger: GoodputLedger, bucket: str):
        self._ledger = ledger
        self._bucket = bucket
        self.duration_s = 0.0

    def __enter__(self) -> "_Spend":
        self._restore = self._ledger.open_bucket
        self._entered_at = self._ledger._clock()
        self._ledger.switch(self._bucket)
        return self

    def __exit__(self, *exc):
        self._ledger.switch(self._restore)
        # wall duration of the WHOLE spend (nested inner spends included) —
        # the switch return value would only be the tail interval
        self.duration_s = self._ledger._clock() - self._entered_at
        return False


# -- step stats / MFU ----------------------------------------------------------

class StepStats:
    """Per-step wall time -> tokens/sec and achieved-vs-peak MFU.

    MFU uses the 6N model-FLOPs-per-token rule (fwd+bwd) over the
    per-generation bf16 peak table — the same roofline bench.py reports
    against, so a live run's ``mfu_ratio`` gauge and the bench's offline
    number are directly comparable.
    """

    def __init__(self, tokens_per_step: int, model_params: int = 0,
                 n_chips: int = 1, accelerator_type: str = "",
                 peak_tflops: Optional[float] = None, window: int = 32):
        self.tokens_per_step = tokens_per_step
        self.model_params = model_params
        self.n_chips = max(1, n_chips)
        self.peak_tflops = (peak_tflops if peak_tflops is not None
                            else peak_tflops_per_chip(accelerator_type))
        self._window = max(1, window)
        self._recent: list[float] = []   # step wall times, newest last
        self.last_step = -1
        self.last_step_s = 0.0
        self.count = 0

    def record(self, step: int, step_time_s: float):
        self.last_step = step
        self.last_step_s = step_time_s
        self.count += 1
        self._recent.append(step_time_s)
        if len(self._recent) > self._window:
            del self._recent[:-self._window]

    @property
    def mean_step_s(self) -> float:
        return sum(self._recent) / len(self._recent) if self._recent else 0.0

    @property
    def median_step_s(self) -> float:
        if not self._recent:
            return 0.0
        vals = sorted(self._recent)
        return vals[len(vals) // 2]

    @property
    def tokens_per_sec(self) -> float:
        mean = self.mean_step_s
        return self.tokens_per_step / mean if mean > 0 else 0.0

    @property
    def mfu(self) -> float:
        """achieved model FLOPs / peak FLOPs, per chip (0 when unknowable)."""
        if not (self.model_params and self.peak_tflops):
            return 0.0
        tok_s_chip = self.tokens_per_sec / self.n_chips
        return (6.0 * self.model_params * tok_s_chip) / (self.peak_tflops * 1e12)

    def summary(self) -> dict:
        return {"step": self.last_step, "steps_recorded": self.count,
                "step_time_s": round(self.last_step_s, 6),
                "mean_step_s": round(self.mean_step_s, 6),
                "tokens_per_sec": round(self.tokens_per_sec, 3),
                "mfu": round(self.mfu, 6)}


# -- straggler / stall watchdog ------------------------------------------------

class StragglerWatchdog:
    """Flags hosts whose step counter stops advancing (stall) or whose step
    time exceeds ``straggler_factor`` x the median across hosts (slow).

    Worker-0 feeds it: its own steps directly, peers' via the heartbeat line
    protocol (``ingest``). ``check()`` returns only NEWLY-flagged events —
    a host stays flagged (no re-emission) until it recovers, so one stall
    episode is one ``training.straggler`` span, not one per sweep.
    """

    def __init__(self, num_hosts: int, straggler_factor: float = 3.0,
                 stall_timeout_s: float = 120.0,
                 clock: Callable[[], float] = time.monotonic,
                 window: int = 8):
        self.num_hosts = max(1, num_hosts)
        self.straggler_factor = straggler_factor
        self.stall_timeout_s = stall_timeout_s
        self._clock = clock
        self._lock = threading.Lock()
        self._window = max(1, window)
        self._started_at = clock()
        # the stall clock for never-reported hosts starts at the FIRST
        # heartbeat from ANY host — while nobody has reported the gang is
        # still compiling (first-step XLA compile routinely exceeds any
        # sane stall timeout) and flagging every host would be noise
        self._first_observed_at: Optional[float] = None
        # host -> (last step, time of last ADVANCE, recent step times)
        self._step: dict[int, int] = {}
        self._advanced_at: dict[int, float] = {}
        self._times: dict[int, list[float]] = {}
        self._flagged: dict[int, str] = {}   # host -> kind, while in episode

    def observe(self, host: int, step: int, step_time_s: float = 0.0,
                now: Optional[float] = None):
        now = self._clock() if now is None else now
        with self._lock:
            if self._first_observed_at is None:
                self._first_observed_at = now
            if step > self._step.get(host, -1):
                self._step[host] = step
                self._advanced_at[host] = now
            if step_time_s > 0:
                ts = self._times.setdefault(host, [])
                ts.append(step_time_s)
                if len(ts) > self._window:
                    del ts[:-self._window]

    def ingest(self, line: str, now: Optional[float] = None) -> bool:
        """Feed one heartbeat-protocol line (POST /heartbeat body, or a log
        line); returns True when it parsed."""
        parsed = parse_heartbeat(line)
        if parsed is None:
            return False
        host, step, step_time_s = parsed
        self.observe(host, step, step_time_s, now=now)
        return True

    def _peer_median_step_s(self, host: int) -> float:
        """Median of the OTHER hosts' mean step times. Excluding the
        candidate keeps a 2-host gang's slow member from being half its own
        median (which made 'slow' structurally unflaggable there)."""
        means = sorted(sum(ts) / len(ts)
                       for h, ts in self._times.items() if ts and h != host)
        if not means:
            return 0.0
        n = len(means)
        if n % 2:
            return means[n // 2]
        return (means[n // 2 - 1] + means[n // 2]) / 2.0

    def check(self, now: Optional[float] = None) -> list[dict]:
        """Newly-flagged straggler events. A host that has NEVER reported
        counts as stalled once the timeout passes from the gang's first
        heartbeat — a dead host must not be invisible just because it said
        nothing, but nobody is flagged while the whole gang is still
        compiling (no heartbeats at all yet)."""
        now = self._clock() if now is None else now
        events: list[dict] = []
        with self._lock:
            if self._first_observed_at is None:
                return []
            for host in range(self.num_hosts):
                since = self._advanced_at.get(host, self._first_observed_at)
                lag = now - since
                times = self._times.get(host, [])
                mean = sum(times) / len(times) if times else 0.0
                median = self._peer_median_step_s(host)
                kind = ""
                if lag > self.stall_timeout_s:
                    kind = "stall"
                elif (median > 0 and mean > self.straggler_factor * median
                      and len(times) >= 2):
                    kind = "slow"
                if kind:
                    if self._flagged.get(host) != kind:
                        self._flagged[host] = kind
                        events.append({
                            "host": host, "kind": kind,
                            "last_step": self._step.get(host, -1),
                            "lag_s": round(lag, 3),
                            "step_time_s": round(mean, 6),
                            "median_step_s": round(median, 6)})
                else:
                    self._flagged.pop(host, None)
        return events

    @property
    def flagged(self) -> dict[int, str]:
        with self._lock:
            return dict(self._flagged)

    def snapshot(self, now: Optional[float] = None) -> dict:
        """Per-host table for /debug/train and the training.run span."""
        now = self._clock() if now is None else now
        with self._lock:
            out = {}
            for host in range(self.num_hosts):
                times = self._times.get(host, [])
                out[str(host)] = {
                    "step": self._step.get(host, -1),
                    "age_s": round(now - self._advanced_at.get(
                        host, self._started_at), 3),
                    "mean_step_s": round(sum(times) / len(times), 6)
                    if times else 0.0,
                    "flagged": self._flagged.get(host, ""),
                }
            return out


# -- restart-attribution state -------------------------------------------------

STATE_FILE = "goodput_state.json"


def state_path_for(checkpoint_dir: str) -> str:
    return os.path.join(checkpoint_dir, STATE_FILE) if checkpoint_dir else ""


def write_state(path: str, *, step: int, unsaved_work_s: float, ts: float,
                attempt: int = 0, resize: int = 0):
    """Atomically persist the running attempt's exposure: how much work
    would be lost if it died right now (productive seconds since the last
    durable checkpoint) plus a wall timestamp for downtime accounting.
    ``attempt``/``resize`` record WHICH launch wrote the state (the requeue
    count and the elastic-resize count), so the next launch can attribute
    the loss to the right cause: ``restart_lost`` for a full requeue,
    ``resize`` for an elastic shrink/grow relaunch."""
    if not path:
        return
    payload = {"step": step, "unsaved_work_s": round(unsaved_work_s, 6),
               "ts": ts, "attempt": attempt, "resize": resize}
    tmp = f"{path}.tmp.{os.getpid()}"  # never share a staging file
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def read_state(path: str) -> Optional[dict]:
    """The raw persisted exposure record, or None when unreadable."""
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            prev = json.load(f)
        return prev if isinstance(prev, dict) else None
    except (OSError, ValueError):
        return None


def read_lost_state(path: str, now: float) -> tuple[float, int]:
    """(lost seconds, prior step) a restarting attempt should charge to
    ``restart_lost``: the prior attempt's unsaved work plus the downtime
    between its last state write and now. (0.0, -1) when unknowable."""
    prev = read_state(path)
    if prev is None:
        return 0.0, -1
    try:
        unsaved = max(0.0, float(prev.get("unsaved_work_s", 0.0)))
        downtime = max(0.0, now - float(prev.get("ts", now)))
        return unsaved + downtime, int(prev.get("step", -1))
    except (ValueError, TypeError):
        return 0.0, -1


# -- async heartbeat poster ----------------------------------------------------

class HeartbeatPoster:
    """Best-effort POST of heartbeat lines to worker-0's telemetry server.

    Same shape as the Tracer's export writer: the step loop pays a bounded
    queue put, never a network round-trip; a dead/slow aggregator drops
    beats (counted) instead of stalling training — the watchdog treats a
    silent host as stalled, which is the correct failure reading anyway.
    """

    def __init__(self, address: str, timeout_s: float = 2.0):
        import queue
        self.url = f"http://{address}/heartbeat"
        self.timeout_s = timeout_s
        self.dropped = 0
        self._q: "queue.Queue[Optional[str]]" = queue.Queue(maxsize=256)
        self._thread = threading.Thread(target=self._drain,
                                        name="heartbeat-poster", daemon=True)
        self._thread.start()

    def __call__(self, line: str):
        import queue
        try:
            self._q.put_nowait(line)
        except queue.Full:
            self.dropped += 1

    def _drain(self):
        import urllib.request
        while True:
            line = self._q.get()
            if line is None:
                return
            try:
                req = urllib.request.Request(
                    self.url, data=line.encode(),
                    headers={"Content-Type": "text/plain"})
                with urllib.request.urlopen(req, timeout=self.timeout_s):
                    pass
            except Exception as e:  # noqa: BLE001 — must never kill a step
                self.dropped += 1
                log.debug("heartbeat POST to %s failed (dropped %d): %s",
                          self.url, self.dropped, e)

    def close(self):
        import queue
        try:
            self._q.put(None, timeout=1.0)
        except queue.Full:
            log.debug("heartbeat queue full at close — abandoning the "
                      "writer after the bounded join")
        self._thread.join(timeout=2.0)


# -- the bundle Trainer feeds --------------------------------------------------

class TrainingTelemetry:
    """Everything one training process records, behind four hooks:
    ``run_started`` / ``record_step`` / ``checkpoint(kind)`` /
    ``run_finished``. Owns the ledger (driving its bucket switches so
    callers can't leave a bucket dangling), the step stats, worker-0's
    watchdog, and the metric/span emission.

    ``emit_line`` receives the protocol lines (heartbeats every step,
    a TPU_TELEMETRY state line every ``telemetry_every`` steps); train_main
    points it at stderr + the worker-0 POSTer, tests capture it.
    """

    def __init__(self, *, tokens_per_step: int, model_params: int = 0,
                 n_chips: int = 1, accelerator_type: str = "",
                 num_hosts: int = 1, host_id: int = 0,
                 metrics=None, tracer=None,
                 clock: Callable[[], float] = time.time,
                 mono: Callable[[], float] = time.monotonic,
                 straggler_factor: float = 3.0,
                 stall_timeout_s: float = 120.0,
                 attempt: int = 0, resize_attempt: int = 0,
                 dp_width: int = 0, state_path: str = "",
                 telemetry_every: int = 1, state_interval_s: float = 10.0,
                 emit_line: Optional[Callable[[str], None]] = None):
        self.metrics = metrics
        self.tracer = tracer
        self.clock = clock
        self.host_id = host_id
        self.num_hosts = max(1, num_hosts)
        self.attempt = attempt
        # elastic gang training (ISSUE 6): resize_attempt is the kubelet's
        # cumulative shrink/grow count (TPU_ELASTIC_RESIZE); dp_width is the
        # current data-parallel width, surfaced on the TPU_TELEMETRY line so
        # the kubelet and goodput_summary can render the resize timeline
        self.resize_attempt = resize_attempt
        self.dp_width = dp_width
        # ONLY worker-0 owns the restart-attribution state: the checkpoint
        # dir is shared across hosts (orbax requires it), and N hosts
        # rewriting one goodput_state.json every step would race — worker-0's
        # view is canonical for the whole gang anyway
        self.state_path = state_path if host_id == 0 else ""
        self.telemetry_every = max(1, telemetry_every)
        # exposure persistence is throttled: a per-step synchronous write
        # would put a (possibly GCS-fuse) filesystem round-trip inside the
        # device-synced hot loop this module exists to time. Downtime is
        # part of the restart charge regardless, so coarse granularity only
        # under-counts by < state_interval_s of unsaved work.
        self.state_interval_s = state_interval_s
        self._state_written_at: Optional[float] = None
        self.emit_line = emit_line
        self.trace_id = tracer.new_trace_id() if tracer is not None else ""
        self.ledger = GoodputLedger(clock=mono, start_bucket="idle")
        self.stats = StepStats(tokens_per_step=tokens_per_step,
                               model_params=model_params, n_chips=n_chips,
                               accelerator_type=accelerator_type)
        # worker-0 aggregates the gang; peers carry a watchdog of size 0
        self.watchdog = (StragglerWatchdog(
            num_hosts, straggler_factor=straggler_factor,
            stall_timeout_s=stall_timeout_s, clock=mono)
            if host_id == 0 else None)
        self.straggler_events = 0
        self._lock = threading.Lock()
        self._productive_at_ckpt = 0.0    # ledger's productive total then
        # an async-STAGED save: (step, productive total at staging). The
        # exposure baseline only moves when the background write is durable
        # (checkpoint_durable, called from Trainer.wait_pending) — resetting
        # at staging would under-count restart_lost for a preemption landing
        # while the write is still in flight.
        self._staged_ckpt: Optional[tuple[int, float]] = None
        self._exported_lost: dict[str, float] = {}
        self.restart_lost_s = 0.0
        self.resize_lost_s = 0.0
        self.resumed_from_step = -1
        if (attempt > 0 or resize_attempt > 0) and state_path:
            # ONE read: the lost amount and the (attempt, resize) pair used
            # to attribute it must come from the same state version — a
            # second read could race a writer and mix versions
            prev = read_state(state_path) or {}
            now = clock()
            try:
                unsaved = max(0.0, float(prev.get("unsaved_work_s", 0.0)))
                lost = unsaved + max(0.0, now - float(prev.get("ts", now)))
                prev_step = int(prev.get("step", -1))
            except (ValueError, TypeError):
                lost, prev_step = 0.0, -1
            if lost > 0:
                # Attribute the prior launch's unsaved work + downtime to the
                # cause of THIS relaunch. A bumped requeue attempt means a
                # full restart (restart_lost); an unchanged attempt with a
                # bumped resize count means the kubelet shrank/grew the gang
                # (the new exclusive `resize` bucket) — so elastic downtime
                # never double-charges restart_lost (the A/B the soak runs).
                prev_attempt = int(prev.get("attempt", 0) or 0)
                prev_resize = int(prev.get("resize", 0) or 0)
                if attempt <= prev_attempt and resize_attempt > prev_resize:
                    self.ledger.charge("resize", lost)
                    self.resize_lost_s = lost
                else:
                    self.ledger.charge("restart_lost", lost)
                    self.restart_lost_s = lost
                self.resumed_from_step = prev_step
        if metrics is not None:
            self._describe(metrics)
            if dp_width:
                metrics.set_gauge("tpu_training_resize_dp_width",
                                  float(dp_width) if resize_attempt else 0.0)

    @staticmethod
    def _describe(m):
        m.describe("tpu_training_step_seconds",
                   "optimizer-step wall time (device-synced)",
                   buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5,
                            10, 30, 60))
        m.describe("tpu_training_tokens_per_second",
                   "training throughput over the recent step window")
        m.describe("tpu_training_mfu_ratio",
                   "achieved model FLOPs / bf16 peak (6N rule, per chip)")
        m.describe("tpu_training_goodput_ratio",
                   "productive seconds / wall seconds (goodput ledger)")
        m.describe("tpu_training_lost_seconds",
                   "non-productive wall seconds by cause (ledger buckets)")
        m.describe("tpu_training_last_step",
                   "last completed optimizer step")
        m.describe("tpu_training_checkpoint_seconds",
                   "blocking checkpoint save/restore time (kind label)")
        m.describe("tpu_training_straggler_events",
                   "hosts newly flagged stalled/slow by the watchdog")
        m.describe("tpu_training_resize_events",
                   "elastic gang resizes seen by this process (kind label: "
                   "shrink/grow)")
        m.describe("tpu_training_resize_seconds",
                   "wall time spent rebuilding the mesh + resharding state "
                   "for an elastic resize",
                   buckets=(0.1, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 300))
        m.describe("tpu_training_resize_dp_width",
                   "current data-parallel width after the last elastic "
                   "resize (0 = never resized)")

    # -- hooks (called by Trainer / train_main) --------------------------------

    def run_started(self, step: int = 0, compiled: bool = False):
        """Loop entered: time accrues to ``compile`` until the first step
        completes (first step = trace+compile), or straight to
        ``productive`` when this process already compiled (bench re-runs)."""
        self.ledger.switch("productive" if compiled else "compile")

    def record_step(self, step: int, step_time_s: float,
                    loss: Optional[float] = None):
        """One optimizer step completed. Closes the open ledger interval
        into whatever phase it was (compile for the first step, productive
        after), records stats/metrics/spans, emits the heartbeat line, and
        runs the straggler sweep on worker-0."""
        closed = self.ledger.switch("productive")
        self.stats.record(step, step_time_s)
        now = self.clock()
        if self.tracer is not None:
            attrs = {"step": step, "host": self.host_id,
                     "tokens": self.stats.tokens_per_step}
            if loss is not None:
                attrs["loss"] = round(loss, 6)
            self.tracer.record("training.step", now - step_time_s, now,
                               trace_id=self.trace_id, attrs=attrs)
        if self.metrics is not None:
            self.metrics.observe("tpu_training_step_seconds", step_time_s)
            self.metrics.set_gauge("tpu_training_tokens_per_second",
                                   self.stats.tokens_per_sec)
            self.metrics.set_gauge("tpu_training_mfu_ratio", self.stats.mfu)
            self.metrics.set_gauge("tpu_training_goodput_ratio",
                                   self.ledger.goodput)
            self.metrics.set_gauge("tpu_training_last_step", float(step))
            self._export_lost()
        if self.emit_line is not None:
            self.emit_line(format_heartbeat(self.host_id, step, step_time_s))
            if step % self.telemetry_every == 0:
                self.emit_line(format_telemetry(self.telemetry_payload()))
        if self.watchdog is not None:
            self.watchdog.observe(self.host_id, step, step_time_s)
            self.check_stragglers()
        if self.state_path:
            mono_now = self.ledger._clock()
            if (self._state_written_at is None
                    or mono_now - self._state_written_at
                    >= self.state_interval_s):
                with self._lock:
                    unsaved = (self.ledger.total("productive")
                               - self._productive_at_ckpt)
                try:
                    write_state(self.state_path, step=step,
                                unsaved_work_s=max(0.0, unsaved), ts=now,
                                attempt=self.attempt,
                                resize=self.resize_attempt)
                    self._state_written_at = mono_now
                except OSError:
                    pass  # read-only checkpoint volume must not kill training
        return closed

    def checkpoint(self, kind: str = "save", step: Optional[int] = None,
                   durable: bool = True):
        """Context manager around a save/restore: charges the
        ``checkpoint_save``/``checkpoint_restore`` bucket, records the
        ``training.checkpoint`` / ``training.restore`` span + histogram.
        ``durable=True`` saves reset the unsaved-work exposure marker;
        ``durable=False`` (async-staged) saves only note the staging point
        — the reset waits for ``checkpoint_durable()``."""
        return _CheckpointSpan(self, kind, step, durable)

    def checkpoint_durable(self):
        """An async-staged save's background write finished (the caller's
        wait-until-finished boundary): move the exposure baseline to the
        STAGING point — steps run while the write was in flight are not in
        the checkpoint and stay exposed."""
        with self._lock:
            staged = self._staged_ckpt
            self._staged_ckpt = None
            if staged is None:
                return
            step, productive_at_stage = staged
            self._productive_at_ckpt = productive_at_stage
            unsaved = self.ledger.total("productive") - productive_at_stage
        if self.state_path:
            try:
                write_state(self.state_path, step=step,
                            unsaved_work_s=max(0.0, unsaved), ts=self.clock(),
                            attempt=self.attempt, resize=self.resize_attempt)
                self._state_written_at = self.ledger._clock()
            except OSError:
                log.debug("state write at durable boundary failed")

    def resize(self, kind: str, *, old_width: int, new_width: int,
               step: Optional[int] = None):
        """Context manager around an IN-PROCESS elastic resize (mesh rebuild
        + reshard-restore): charges the exclusive ``resize`` ledger bucket,
        records a ``training.resize`` span (kind=shrink/grow, old/new DP
        width) and the ``tpu_training_resize_*`` metrics, and updates the
        advertised ``dp_width``. A kubelet-driven resize RELAUNCH instead
        charges the bucket at boot via ``resize_attempt`` (see __init__)."""
        if kind not in ("shrink", "grow"):
            raise ValueError(f"resize kind must be shrink/grow, not {kind!r}")
        return _ResizeSpan(self, kind, old_width, new_width, step)

    def ingest_heartbeat(self, body: str):
        """POST /heartbeat sink (worker-0): one or more protocol lines."""
        if self.watchdog is None:
            return
        for line in body.splitlines():
            if line.strip():
                self.watchdog.ingest(line)

    def check_stragglers(self, now: Optional[float] = None) -> list[dict]:
        """Run the watchdog sweep (worker-0): emit a ``training.straggler``
        span + structured log line + counter per newly-flagged host, and
        reattribute ledger time to ``stalled`` while any host is flagged."""
        if self.watchdog is None:
            return []
        events = self.watchdog.check(now=now)
        for ev in events:
            self.straggler_events += 1
            wall = self.clock()
            if self.tracer is not None:
                self.tracer.record("training.straggler", wall, wall,
                                   trace_id=self.trace_id,
                                   attrs=dict(ev))
            if self.metrics is not None:
                self.metrics.incr("tpu_training_straggler_events",
                                  labels={"host": str(ev["host"]),
                                          "kind": ev["kind"]})
            if self.emit_line is not None:
                self.emit_line(
                    f"TPU_STRAGGLER host={ev['host']} kind={ev['kind']} "
                    f"last_step={ev['last_step']} lag_s={ev['lag_s']}")
        flagged = self.watchdog.flagged
        if flagged and self.ledger.open_bucket == "productive":
            self.ledger.switch("stalled")
        elif not flagged and self.ledger.open_bucket == "stalled":
            self.ledger.switch("productive")
        return events

    def run_finished(self, extra: Optional[dict] = None) -> dict:
        """Loop exited: close into ``idle``, emit the ``training.run`` span
        carrying the full ledger snapshot (the goodput report's source of
        truth — tools/goodput_summary.py renders it), and return the
        summary fields callers merge into their result dict."""
        self.ledger.switch("idle")
        snap = self.snapshot()
        if self.metrics is not None:
            self.metrics.set_gauge("tpu_training_goodput_ratio",
                                   snap["goodput"])
            self._export_lost()
        if self.tracer is not None:
            attrs = {"attempt": self.attempt, "goodput": snap["goodput"],
                     "mfu": snap["mfu"], "wall_s": snap["wall_s"],
                     "step": snap["step"],
                     "tokens_per_sec": snap["tokens_per_sec"],
                     "buckets": snap["buckets"]}
            if self.dp_width:
                attrs["dp_width"] = self.dp_width
            if self.resize_attempt:
                attrs["resize"] = self.resize_attempt
            if self.watchdog is not None:
                attrs["hosts"] = self.watchdog.snapshot()
            if extra:
                attrs.update(extra)
            self.tracer.record("training.run", self.clock() - snap["wall_s"],
                               self.clock(), trace_id=self.trace_id,
                               attrs=attrs)
        if self.emit_line is not None:
            self.emit_line(format_telemetry(self.telemetry_payload()))
        return {"goodput": snap["goodput"], "mfu": snap["mfu"],
                "lost_s": snap["lost_s"]}

    # -- views -----------------------------------------------------------------

    def _export_lost(self):
        """Counter semantics over the monotone ledger totals: incr deltas
        since the last export, per cause."""
        snap = self.ledger.snapshot()
        for cause, total in snap["buckets"].items():
            if cause == "productive" or total <= 0:
                continue
            prev = self._exported_lost.get(cause, 0.0)
            if total > prev:
                self.metrics.incr("tpu_training_lost_seconds", total - prev,
                                  labels={"cause": cause})
                self._exported_lost[cause] = total

    def telemetry_payload(self) -> dict:
        """The compact TPU_TELEMETRY line body (kubelet scrape surface)."""
        s = self.stats
        out = {"step": s.last_step, "tokens_per_sec": round(s.tokens_per_sec, 3),
               "mfu": round(s.mfu, 6), "goodput": round(self.ledger.goodput, 6),
               "attempt": self.attempt, "host": self.host_id,
               "stalled": bool(self.watchdog.flagged)
               if self.watchdog is not None else False}
        # preemption-cost exposure (ISSUE 19): productive seconds since
        # the last DURABLE checkpoint — what a preemption right now would
        # destroy. Same number the crash-recovery state file records; the
        # kubelet's scrape feeds it to the fleet scheduler, which evicts
        # best-effort gangs lowest-loss-first.
        with self._lock:
            unsaved = (self.ledger.total("productive")
                       - self._productive_at_ckpt)
        out["unsaved_work_s"] = round(max(0.0, unsaved), 3)
        if self.dp_width:
            out["dp_width"] = self.dp_width
        if self.resize_attempt:
            out["resize"] = self.resize_attempt
        return out

    def snapshot(self) -> dict:
        """The /debug/train statusz payload."""
        led = self.ledger.snapshot()
        out = {"step": self.stats.last_step,
               "tokens_per_sec": round(self.stats.tokens_per_sec, 3),
               "mfu": round(self.stats.mfu, 6),
               "step_time_s": round(self.stats.last_step_s, 6),
               "mean_step_s": round(self.stats.mean_step_s, 6),
               "goodput": led["goodput"], "wall_s": led["wall_s"],
               "buckets": led["buckets"], "lost_s": led["lost_s"],
               "attempt": self.attempt, "host": self.host_id,
               "num_hosts": self.num_hosts,
               "restart_lost_s": round(self.restart_lost_s, 6),
               "resize_lost_s": round(self.resize_lost_s, 6),
               "resize_attempt": self.resize_attempt,
               "dp_width": self.dp_width,
               "straggler_events": self.straggler_events}
        if self.watchdog is not None:
            out["hosts"] = self.watchdog.snapshot()
            out["stalled_hosts"] = sorted(self.watchdog.flagged)
        return out


class _ResizeSpan:
    def __init__(self, tel: TrainingTelemetry, kind: str, old_width: int,
                 new_width: int, step: Optional[int]):
        self._tel = tel
        self._kind = kind
        self._old = old_width
        self._new = new_width
        self._step = step
        self.duration_s = 0.0

    def __enter__(self) -> "_ResizeSpan":
        self._spend = self._tel.ledger.spend("resize")
        self._spend.__enter__()
        self._start_wall = self._tel.clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._spend.__exit__(exc_type, exc, tb)
        self.duration_s = self._spend.duration_s
        tel = self._tel
        step = self._step if self._step is not None else tel.stats.last_step
        if exc_type is None:
            tel.resize_attempt += 1
            tel.dp_width = self._new
            # the new width changes tokens-per-chip math only through the
            # caller's batch rescale; stats keep their tokens_per_step, which
            # the caller updates when the global batch changed
        attrs = {"kind": self._kind, "old_width": self._old,
                 "new_width": self._new, "step": step,
                 "resize": tel.resize_attempt}
        if exc_type is not None:
            attrs["error"] = exc_type.__name__
        if tel.tracer is not None:
            tel.tracer.record("training.resize", self._start_wall,
                              self._start_wall + self.duration_s,
                              trace_id=tel.trace_id, attrs=attrs)
        if tel.metrics is not None:
            tel.metrics.incr("tpu_training_resize_events",
                             labels={"kind": self._kind})
            tel.metrics.observe("tpu_training_resize_seconds", self.duration_s)
            if exc_type is None:
                # a FAILED resize never reached the new width — the gauge
                # must keep advertising the width the gang actually runs at
                tel.metrics.set_gauge("tpu_training_resize_dp_width",
                                      float(self._new))
        return False


class _CheckpointSpan:
    def __init__(self, tel: TrainingTelemetry, kind: str, step: Optional[int],
                 durable: bool = True):
        if kind not in ("save", "restore"):
            raise ValueError(f"checkpoint kind must be save/restore, not {kind!r}")
        self._tel = tel
        self._kind = kind
        self._step = step
        self._durable = durable
        self.duration_s = 0.0

    def __enter__(self) -> "_CheckpointSpan":
        self._spend = self._tel.ledger.spend(f"checkpoint_{self._kind}")
        self._spend.__enter__()
        self._start_wall = self._tel.clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._spend.__exit__(exc_type, exc, tb)
        self.duration_s = self._spend.duration_s
        tel = self._tel
        step = self._step if self._step is not None else tel.stats.last_step
        if self._kind == "save" and exc_type is None:
            if self._durable:
                # durable checkpoint: exposure (work lost if we die now)
                # resets — in memory AND in the persisted state, so a
                # process that dies right after its final save doesn't
                # charge the next attempt for work that is durable
                with tel._lock:
                    tel._productive_at_ckpt = tel.ledger.total("productive")
                    tel._staged_ckpt = None  # superseded
                if tel.state_path:
                    try:
                        write_state(tel.state_path, step=step,
                                    unsaved_work_s=0.0, ts=tel.clock(),
                                    attempt=tel.attempt,
                                    resize=tel.resize_attempt)
                        tel._state_written_at = tel.ledger._clock()
                    except OSError:
                        log.debug("state write after save failed (stale "
                                  "unsaved_work_s until next step)")
            else:
                # async-staged: NOT durable yet — remember the staging
                # point; checkpoint_durable() moves the baseline there once
                # the caller's wait-until-finished boundary passes
                with tel._lock:
                    tel._staged_ckpt = (step,
                                        tel.ledger.total("productive"))
        if tel.tracer is not None:
            name = ("training.checkpoint" if self._kind == "save"
                    else "training.restore")
            attrs = {"step": step, "kind": self._kind}
            if exc_type is not None:
                attrs["error"] = exc_type.__name__
            tel.tracer.record(name, self._start_wall,
                              self._start_wall + self.duration_s,
                              trace_id=tel.trace_id, attrs=attrs)
        if tel.metrics is not None:
            tel.metrics.observe("tpu_training_checkpoint_seconds",
                                self.duration_s,
                                labels={"kind": self._kind})
        return False
