"""Single-chip MNIST training workload (BASELINE.json config 2).

The pod command for the v5e-1 smoke test: trains the Flax CNN and prints one
status line per epoch + a final JSON summary the integration harness can parse.
Uses the real MNIST if an npz is provided (no-egress images can't download),
else deterministic synthetic digits that are still learnable.

Run: python -m k8s_runpod_kubelet_tpu.workloads.mnist_train [--steps N]
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..models.mnist import MnistCNN


def load_data(npz_path: str = "", n: int = 4096):
    if npz_path:
        d = np.load(npz_path)
        return (d["x_train"].astype(np.float32)[..., None] / 255.0,
                d["y_train"].astype(np.int32))
    # synthetic learnable digits: class k = blob at a class-specific position
    rng = np.random.RandomState(0)
    ys = rng.randint(0, 10, size=n).astype(np.int32)
    xs = rng.rand(n, 28, 28, 1).astype(np.float32) * 0.15
    for i, y in enumerate(ys):
        r, c = 3 + (y % 5) * 4, 3 + (y // 5) * 10
        xs[i, r:r + 6, c:c + 6, 0] += 0.9
    return xs, ys


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--data", default="")
    args = p.parse_args(argv)

    xs, ys = load_data(args.data)
    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(0), xs[:2])["params"]
    tx = optax.adam(args.lr)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, x, y):
        def loss_fn(p):
            logits = model.apply({"params": p}, x)
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
            acc = jnp.mean(jnp.argmax(logits, -1) == y)
            return loss, acc
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss, acc

    t0 = time.perf_counter()
    first_step_s = None
    loss = acc = None
    for i in range(args.steps):
        idx = np.random.RandomState(i).randint(0, len(xs), args.batch)
        params, opt_state, loss, acc = step(params, opt_state, xs[idx], ys[idx])
        if first_step_s is None:
            jax.block_until_ready(loss)
            first_step_s = time.perf_counter() - t0
        if i % 100 == 0:
            print(f"step {i}: loss={float(loss):.4f} acc={float(acc):.3f}",
                  flush=True)
    jax.block_until_ready(loss)
    summary = {"workload": "mnist", "backend": jax.default_backend(),
               "steps": args.steps, "final_loss": float(loss),
               "final_acc": float(acc), "first_step_s": round(first_step_s, 3),
               "wall_s": round(time.perf_counter() - t0, 2)}
    print(json.dumps(summary), flush=True)
    return 0 if float(acc) > 0.9 else 1


if __name__ == "__main__":
    raise SystemExit(main())
