"""Runnable workloads: training (MaxText-style) and serving (JetStream-style).

These are what the kubelet's pods actually run — the in-repo implementations of
the north-star workloads (BASELINE.json configs 2-5).
"""

from .train import TrainConfig, Trainer, make_train_step, synthetic_batches

__all__ = ["TrainConfig", "Trainer", "make_train_step", "synthetic_batches"]
