"""Runnable workloads: training (MaxText-style) and serving (JetStream-style).

These are what the kubelet's pods actually run — the in-repo implementations of
the north-star workloads (BASELINE.json configs 2-5).

Imports are LAZY: ``workloads.telemetry`` is dependency-free and the kubelet
imports it (provider/training_watch.py parses the telemetry line protocol);
an eager ``from .train import ...`` here would drag jax into the control
plane just to reach a stdlib module.
"""

_TRAIN_EXPORTS = ("TrainConfig", "Trainer", "make_train_step",
                  "synthetic_batches")

__all__ = list(_TRAIN_EXPORTS)


def __getattr__(name):
    if name in _TRAIN_EXPORTS:
        from . import train
        return getattr(train, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
