"""Serving flight recorder: per-step engine timeline + XLA recompile
watchdog (ISSUE 17).

The engine's request spans answer "why was THIS request slow?"; nothing
answered "what did the ENGINE do on step 48123?" — the PR 12 jit-cache
flap was found by eyeballing compile logs, and the bench rounds carry
opaque step medians with no phase attribution. Two dependency-free
pieces (stdlib only, tracing.py's design constraints):

- **FlightRecorder**: a bounded ring of per-decode-step records — batch
  composition, the step wall time split into contiguous
  schedule/kernel/sample/commit phases (phase marks telescope, so the
  phases SUM to the step wall time by construction), arena page
  occupancy, speculative accounting, chunk-interleave events — plus a
  bounded per-request accumulator the engine folds into its
  ``serving.request`` spans. Served at ``GET /debug/steps``.
- **CompileWatchdog**: wraps the engine's hot-path jits in a
  compile-tracking seam (jax.jit's compile cache grows exactly when a
  call compiled), counts ``tpu_serving_recompiles{fn=}``, records a loud
  ``serving.recompile`` span with the old/new abstract-value diff, and
  log-once warns when a hot function compiles past its budget —
  mechanizing the PR 12 bug class (an out_shardings normalization flip
  recompiled the paged step every other batch) the way graftlint
  mechanized review findings.

Threading: phase marks (``step_begin``/``mark``/``step_end``) are
engine-thread-only and lock-free on the hot path; the ring and the
per-request table are guarded by one lock so ``snapshot()`` (HTTP
threads) and ``pop_request`` (engine thread) stay consistent. ``event()``
may be called from any thread.

Overhead discipline: a disabled recorder is ``None`` on the engine — the
hot path pays one attribute load and an ``is not None`` test per mark
site, nothing else. The watchdog's per-call cost is one ``_cache_size()``
read (a dict ``len`` under the jit wrapper); fingerprints are computed
only when a compile is DETECTED, never per call.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Optional

log = logging.getLogger(__name__)

# phase marks in hot-path order; step_end closes "commit"
PHASES = ("schedule", "kernel", "sample", "commit")

# decode steps live in the single-digit-millisecond to ~100ms band on
# real hardware (CPU smoke runs slower); the TTFT ladder's 0.5s first
# bucket would crush every sample into one bin
STEP_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0)


class FlightRecorder:
    """Bounded ring of per-decode-step records + per-request attribution.

    ``max_steps`` bounds the record count and ``max_bytes`` bounds the
    ring's serialized size (each record is JSON-sized once at append;
    oldest records evict until both bounds hold) — the ring can never
    exceed its byte budget no matter how attr-heavy the steps get.
    ``perf`` is the engine's ``_perf`` seam (perf_counter, injectable),
    so the deterministic soaks drive phase math from a fake clock."""

    def __init__(self, max_steps: int = 512, max_bytes: int = 262144,
                 perf: Callable[[], float] = time.perf_counter,
                 metrics=None, max_requests: int = 64):
        if max_steps < 1:
            raise ValueError(f"max_steps must be >= 1, got {max_steps}")
        if max_bytes < 1024:
            raise ValueError(f"max_bytes must be >= 1024, got {max_bytes}")
        self.max_steps = max_steps
        self.max_bytes = max_bytes
        self.max_requests = max_requests
        self._perf = perf
        self.metrics = metrics
        self._lock = threading.Lock()
        # ring of (record_dict, serialized_bytes); bytes tracked so the
        # budget is enforced on real serialized size, not a guess
        self._ring: "deque[tuple[dict, int]]" = deque()
        self._bytes = 0
        self._seq = 0
        self.dropped_records = 0
        # per-request phase accumulators, folded into serving.request
        # spans at completion; bounded FIFO-drop-oldest (a dropped entry
        # costs one request its step attribution, never memory)
        self._by_request: "OrderedDict[str, dict]" = OrderedDict()
        # engine-thread step state (no lock: marks never cross threads)
        self._t0: Optional[float] = None
        self._marks: list[tuple[str, float]] = []

    # -- hot path (engine thread only) -----------------------------------------

    def step_begin(self):
        """Arm a step: t0 for the schedule phase (slot-table growth,
        lengths/page-table assembly)."""
        self._t0 = self._perf()
        self._marks = []

    def mark(self, phase: str):
        """Close the named phase at now; the next phase opens here."""
        if self._t0 is None:
            return
        self._marks.append((phase, self._perf()))

    def step_end(self, mode: str = "decode", active: int = 0,
                 draining: bool = False, paged: bool = False,
                 spec_k: int = 0, adapters: int = 0, tokens: int = 0,
                 rids: Optional[list] = None, arena: Optional[dict] = None,
                 spec: Optional[dict] = None, interleaved: bool = False):
        """Close the step ("commit" phase ends now), build the record,
        observe the step histograms, and charge the step to ``rids``."""
        if self._t0 is None:
            return
        t_end = self._perf()
        phases: dict[str, float] = {}
        prev = self._t0
        for name, t in self._marks:
            phases[name] = phases.get(name, 0.0) + (t - prev)
            prev = t
        phases["commit"] = phases.get("commit", 0.0) + (t_end - prev)
        wall = t_end - self._t0
        self._t0 = None
        self._marks = []
        record = {
            "seq": self._seq,
            "t": round(t_end, 6),
            "wall_s": wall,
            "phases": {f"{p}_s": phases.get(p, 0.0) for p in PHASES},
            "batch": {"mode": mode, "active": active,
                      "draining": bool(draining), "paged": bool(paged),
                      "spec_k": spec_k, "adapters": adapters,
                      "interleaved": bool(interleaved)},
            "tokens": tokens,
        }
        if arena:
            record["arena"] = arena
        if spec:
            record["spec"] = spec
        self._seq += 1
        self._append(record)
        if self.metrics is not None:
            m = self.metrics
            m.observe("tpu_serving_step_wall_seconds", wall)
            # one literal per phase (not a loop over PHASES): the
            # observability lint reads names statically
            m.observe("tpu_serving_step_schedule_seconds",
                      phases.get("schedule", 0.0))
            m.observe("tpu_serving_step_kernel_seconds",
                      phases.get("kernel", 0.0))
            m.observe("tpu_serving_step_sample_seconds",
                      phases.get("sample", 0.0))
            m.observe("tpu_serving_step_commit_seconds",
                      phases.get("commit", 0.0))
            m.observe("tpu_serving_step_tokens", float(tokens))
        if rids:
            share = wall / len(rids)
            with self._lock:
                for rid in rids:
                    acc = self._by_request.get(rid)
                    if acc is None:
                        while len(self._by_request) >= self.max_requests:
                            self._by_request.popitem(last=False)
                        acc = self._by_request[rid] = {
                            "steps": 0, "step_wall_s": 0.0,
                            "kernel_s": 0.0}
                    acc["steps"] += 1
                    acc["step_wall_s"] += share
                    acc["kernel_s"] += phases.get("kernel", 0.0) / len(rids)

    def event(self, kind: str, **attrs):
        """Out-of-band timeline entry (chunk-interleave yields, prefill
        chunk completions); any thread."""
        record = {"seq": self._seq, "t": round(self._perf(), 6),
                  "event": kind}
        if attrs:
            record.update(attrs)
        self._seq += 1
        self._append(record)

    def _append(self, record: dict):
        try:
            # compact separators: sized AND stored compact, so the byte
            # budget buys more records and the dumps stays cheap
            nbytes = len(json.dumps(record, separators=(",", ":")))
        except (TypeError, ValueError):
            # a non-serializable attr must never kill the engine thread
            with self._lock:
                self.dropped_records += 1
            return
        with self._lock:
            if nbytes > self.max_bytes:
                self.dropped_records += 1
                return
            self._ring.append((record, nbytes))
            self._bytes += nbytes
            while (len(self._ring) > self.max_steps
                   or self._bytes > self.max_bytes):
                _, old = self._ring.popleft()
                self._bytes -= old
            n_records, n_bytes = len(self._ring), self._bytes
        # occupancy gauges refresh every 16th append (plus first): the
        # ring turns over hundreds of times between scrapes, so per-append
        # gauge writes are pure hot-path cost with no observability gain
        if self.metrics is not None and (self._seq & 0xF) == 1:
            self.metrics.set_gauge("tpu_serving_step_ring_records",
                                   n_records)
            self.metrics.set_gauge("tpu_serving_step_ring_bytes", n_bytes)

    # -- request attribution ---------------------------------------------------

    def pop_request(self, rid: str) -> Optional[dict]:
        """Take (and forget) a request's accumulated step attribution —
        the engine folds it into the serving.request span at completion."""
        with self._lock:
            return self._by_request.pop(rid, None)

    # -- reads -----------------------------------------------------------------

    @property
    def ring_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def records(self, n: int = 0) -> list[dict]:
        """The newest ``n`` records (0 = all), oldest first."""
        with self._lock:
            recs = [r for r, _ in self._ring]
        return recs[-n:] if n else recs

    def rollup(self) -> dict:
        """Phase medians + batch composition over the current ring — the
        /debug/steps summary (and the bench cell's in-row numbers)."""
        with self._lock:
            recs = [r for r, _ in self._ring]
            nbytes = self._bytes
            dropped = self.dropped_records
        steps = [r for r in recs if "wall_s" in r]
        out: dict[str, Any] = {
            "records": len(recs), "steps": len(steps),
            "events": len(recs) - len(steps), "bytes": nbytes,
            "max_bytes": self.max_bytes, "dropped": dropped,
        }
        if not steps:
            return out
        out["wall_ms_p50"] = _median([r["wall_s"] for r in steps]) * 1e3
        for p in PHASES:
            out[f"{p}_ms_p50"] = _median(
                [r["phases"].get(f"{p}_s", 0.0) for r in steps]) * 1e3
        out["active_p50"] = _median(
            [r["batch"]["active"] for r in steps])
        out["tokens_total"] = sum(r.get("tokens", 0) for r in steps)
        out["spec_steps"] = sum(
            1 for r in steps if r["batch"]["mode"] == "spec_verify")
        return out

    def snapshot(self, n: int = 64) -> dict:
        """The /debug/steps payload: a JSONL-ready tail plus the rollup."""
        return {"enabled": True, "steps": self.records(n),
                "rollup": self.rollup()}


def _median(vals: list) -> float:
    s = sorted(vals)
    return float(s[len(s) // 2]) if s else 0.0


# -- compile watchdog ----------------------------------------------------------


def _fingerprint(args: tuple, kwargs: dict, depth: int = 0) -> list[str]:
    """Duck-typed abstract-value summary of a call's arguments: leaves
    render as ``dtype[shape]@sharding`` via getattr (no jax import — the
    module stays dependency-free and the fingerprints work on any array
    library). Computed ONLY when a compile was detected; the diff of two
    fingerprints is the serving.recompile span's payload."""
    out: list[str] = []

    def walk(x, path):
        if len(out) >= 512:  # bound pathological pytrees
            return
        if isinstance(x, dict):
            for k in sorted(x, key=str):
                walk(x[k], f"{path}.{k}")
        elif isinstance(x, (list, tuple)):
            for i, v in enumerate(x):
                walk(v, f"{path}[{i}]")
        elif x is None or isinstance(x, (bool, int, float, str)):
            out.append(f"{path}={x!r}")
        else:
            aval = getattr(x, "aval", None)
            shape = getattr(x, "shape", None)
            dtype = getattr(x, "dtype", None)
            sharding = getattr(x, "sharding", None)
            if aval is not None:
                desc = str(aval)
            elif shape is not None:
                desc = f"{dtype}{tuple(shape)}"
            else:
                desc = type(x).__name__
            if sharding is not None:
                desc += f"@{sharding}"
            out.append(f"{path}:{desc}")

    for i, a in enumerate(args):
        walk(a, f"a{i}")
    for k in sorted(kwargs):
        walk(kwargs[k], f"kw.{k}")
    return out


def _diff(old: list[str], new: list[str], limit: int = 8) -> list[str]:
    """First few positions where two fingerprints disagree (the avals
    that CHANGED are the recompile's cause)."""
    changes = []
    o_set = set(old)
    for line in new:
        if line not in o_set:
            changes.append(f"+{line}")
            if len(changes) >= limit:
                return changes
    n_set = set(new)
    for line in old:
        if line not in n_set:
            changes.append(f"-{line}")
            if len(changes) >= limit:
                break
    return changes


class _TrackedJit:
    """One wrapped jit: passes calls straight through, then reads the
    wrapper's compile-cache size — growth means THIS call compiled."""

    __slots__ = ("name", "fn", "budget", "_watchdog", "_size", "compiles",
                 "_last_fp", "_warned")

    def __init__(self, watchdog: "CompileWatchdog", name: str, fn,
                 budget: Optional[int]):
        self.name = name
        self.fn = fn
        self.budget = budget
        self._watchdog = watchdog
        self._size = self._cache_size()
        self.compiles = 0
        self._last_fp: Optional[list[str]] = None
        self._warned = False

    def _cache_size(self) -> Optional[int]:
        # jax.jit wrappers expose _cache_size() (0.4.x); a toolchain
        # without it degrades to no detection, never to a crash
        getter = getattr(self.fn, "_cache_size", None)
        if getter is None:
            return None
        try:
            return int(getter())
        except Exception as e:  # noqa: BLE001 — introspection must never fail a step
            log.debug("compile-cache introspection of %s failed "
                      "(watchdog degrades to no detection): %s",
                      self.name, e)
            return None

    def __call__(self, *args, **kwargs):
        out = self.fn(*args, **kwargs)
        size = self._cache_size()
        if size is not None and self._size is not None \
                and size > self._size:
            self._size = size
            self._on_compile(args, kwargs)
        elif size is not None:
            self._size = size
        return out

    def poll(self):
        """Cache-size check WITHOUT a call — for shared module-level jits
        the engine cannot wrap (the sampler fns), polled once per step."""
        size = self._cache_size()
        if size is not None and self._size is not None \
                and size > self._size:
            self._size = size
            self._on_compile((), {})

    def _on_compile(self, args: tuple, kwargs: dict):
        self.compiles += 1
        fp = _fingerprint(args, kwargs) if (args or kwargs) else None
        self._watchdog._compiled(self, fp)
        self._last_fp = fp if fp is not None else self._last_fp

    def snapshot(self) -> dict:
        return {"compiles": self.compiles,
                "recompiles": max(0, self.compiles - 1),
                "budget": self.budget, "warned": self._warned}


class CompileWatchdog:
    """Tracks compiles across the engine's hot-path jits.

    ``wrap(name, fn, budget)`` returns a call-compatible ``_TrackedJit``
    (None passes through, so optional jits wire transparently);
    ``attach`` registers a shared module-level jit for per-step polling
    instead. Every compile past a function's FIRST increments
    ``tpu_serving_recompiles{fn=}`` and records a ``serving.recompile``
    span carrying the fingerprint diff; compiles past ``budget`` trip a
    log-once warning. Bucket-compiling functions (prefill/chunk/insert —
    one legitimate compile per prompt-length bucket) pass ``budget=None``
    to keep tracking without the alarm."""

    DEFAULT_BUDGET = 2

    def __init__(self, metrics=None, tracer=None):
        self.metrics = metrics
        self.tracer = tracer
        self._tracked: dict[str, _TrackedJit] = {}
        self._polled: list[_TrackedJit] = []

    def wrap(self, name: str, fn, budget: Optional[int] = DEFAULT_BUDGET):
        if fn is None:
            return None
        tracked = _TrackedJit(self, name, fn, budget)
        self._tracked[name] = tracked
        if self.metrics is not None and budget is not None:
            # zero-seed at wrap: the per-fn series must exist before the
            # first (expected) compile, so dashboards alert on ANY rise
            self.metrics.incr("tpu_serving_recompiles", 0,
                              labels={"fn": name})
        return tracked

    def attach(self, name: str, fn, budget: Optional[int] = None):
        """Track a jit the engine doesn't own (module-level, shared
        across engines) by polling its cache size once per decode step
        (``poll()``): compile attribution is step-granular instead of
        call-granular, which is exactly enough to catch a flap."""
        if fn is None:
            return
        tracked = _TrackedJit(self, name, fn, budget)
        self._tracked[name] = tracked
        self._polled.append(tracked)
        if self.metrics is not None and budget is not None:
            self.metrics.incr("tpu_serving_recompiles", 0,
                              labels={"fn": name})

    def poll(self):
        for tracked in self._polled:
            tracked.poll()

    def _compiled(self, tracked: _TrackedJit, fp: Optional[list[str]]):
        if tracked.compiles <= 1:
            return  # the first compile is the contract, not a finding
        # the counter covers only ALARMED fns (budget set): bucketed fns
        # legitimately compile once per shape, so counting them would
        # make "recompiles > 0" useless as an alert condition — their
        # full counts still show in snapshot()/debug/steps
        if self.metrics is not None and tracked.budget is not None:
            self.metrics.incr("tpu_serving_recompiles",
                              labels={"fn": tracked.name})
        if self.tracer is not None:
            try:
                now = self.tracer.clock()
                diff = (_diff(tracked._last_fp, fp)
                        if tracked._last_fp and fp else [])
                self.tracer.record(
                    "serving.recompile", now, now,
                    attrs={"fn": tracked.name,
                           "compiles": tracked.compiles,
                           "aval_diff": diff})
            except Exception:  # noqa: BLE001 — tracing must never fail a step
                log.exception("recompile span for %s failed", tracked.name)
        if (tracked.budget is not None
                and tracked.compiles > tracked.budget
                and not tracked._warned):
            tracked._warned = True
            log.warning(
                "serving: hot-path jit %r compiled %d times (budget %d) — "
                "a cache-key flap (changed avals/shardings/donation "
                "pattern) is recompiling the hot loop; see the "
                "serving.recompile spans for the aval diff",
                tracked.name, tracked.compiles, tracked.budget)

    def snapshot(self) -> dict:
        """Per-fn compile counts — /debug/steps carries this next to the
        step ring (and the bench cell records it in-row)."""
        return {name: t.snapshot()
                for name, t in sorted(self._tracked.items())}

    def total_recompiles(self) -> int:
        return sum(max(0, t.compiles - 1) for t in self._tracked.values())
