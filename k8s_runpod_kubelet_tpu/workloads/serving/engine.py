"""JetStream-style serving engine: continuous batching over fixed decode slots.

The in-repo implementation of the autoscaled-serving workload (BASELINE.json
config 5). TPU-first decisions:

- **Fixed-shape decode**: the decode step is one jitted program over a constant
  (slots, cache_len) batch — no recompilation as requests come and go; slots
  activate/freeze via a boolean mask.
- **Prefill/decode split**: prompts prefill as single-request batches (their
  own jit) on a dedicated PREFILL THREAD; the engine thread only pops
  ready-made caches and inserts them into free slots (a cheap donated-buffer
  update), so the decode loop never blocks on a long prompt's attention
  (VERDICT r1 item 8: the round-1 engine ran prefill synchronously between
  decode steps). The ready queue is bounded to the slot count, so at most
  ``slots`` prefilled-but-not-inserted caches hold HBM at once.
- **HPA signal**: queue depth + slot utilization are exported via Metrics; the
  Helm chart scales serving pods on tpu_serving_queue_depth (SURVEY.md §5.5
  gap — the reference has no metrics at all).
- **Cache economics**: the engine cache is DONATED through the decode jit
  (in-place updates, not full-cache copies per step); sliding-window models
  ring at O(window) memory (Gemma-2/3 interleaves split local-ring/
  global-full); optional int8 KV halves cache read bandwidth.
- **Paged prefix KV pool** (ISSUE 8): every prompt is matched against a
  radix trie of page-granular shared KV (kv_manager.py) — matched pages
  GATHER from one preallocated HBM arena instead of re-prefilling, every
  prefill's full pages are cached back (refcounted, LRU-leaf eviction),
  and register_prefix() pins trie paths instead of whole single-slot
  caches. At fleet scale this is what makes the router's prefix-affinity
  pay off in TTFT and KV bytes.
- **Multi-tenant**: prefix caching (shared system prompts prefill once),
  multi-LoRA (per-request adapters inside one decode batch), per-request
  seeds/stop sequences/logprobs, speculative decoding.

Threading: callers submit() from anywhere; one engine thread owns the model
state (JAX objects never cross threads mid-step). The prefix pool (trie +
arena) is shared by the prefill thread and register_prefix callers — every
access runs under ``_prefix_lock`` (arena writes DONATE buffers, so even
reads must not race them).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
import uuid
from collections import OrderedDict
from concurrent.futures import Future
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...metrics import Metrics
from ...models.llama import LlamaConfig, LlamaModel, Params
from ...tracing import Tracer
from .costmeter import CostMeter
from .kv_manager import DensePrefixStore, PagedKVStore, kv_cache_pspec  # noqa: F401 — kv_cache_pspec re-exported (layout contract)
from .recorder import STEP_BUCKETS, CompileWatchdog, FlightRecorder
from .sampler import (_apply_penalties, _bias_row, _bump_counts,
                      _logit_modded, _penalized, _row_keys, _sample,
                      _sample_filtered, _sample_plain, _set_count_row)
from .scheduler import (ITL_BUCKETS, TTFT_BUCKETS, UTIL_BUCKETS,
                        ChunkArbiter, EngineDraining, EngineOverloaded,
                        Request, ServingConfig, _fail_future, _Slot)

log = logging.getLogger(__name__)


class _PagedRun:
    """A prefill that ran PAGED-NATIVE (ISSUE 14): the prompt's KV already
    sits in arena pages this run holds references to — there is no dense
    single-request cache. Travels the ready queue in the `single` position;
    _bind_paged_slot transfers the page run to the slot wholesale (no
    match_full, no alloc, no fill_pages copy). ``store`` pins which arena
    the pages belong to: a crash-recovery rebuild discards the old store
    wholesale, so a stale run must fail its request, never bind."""

    __slots__ = ("pages", "kv_len", "store")

    def __init__(self, pages: list, kv_len: int, store):
        self.pages = pages
        self.kv_len = kv_len
        self.store = store


class ServingEngine:
    def __init__(self, cfg: LlamaConfig, params: Params, sc: ServingConfig,
                 metrics: Optional[Metrics] = None, seed: int = 0,
                 decode_fn=None, mesh=None, tracer: Optional[Tracer] = None,
                 perf=None):
        self.cfg = cfg
        self.sc = sc
        # duration clock for TTFT/ITL/queue-wait stamps and span math
        # (perf_counter: monotonic, ns resolution); injectable so stress
        # tests measure with a deterministic clock
        self._perf = perf if perf is not None else time.perf_counter
        # per-request span source (queue-wait/prefill/decode/finish trees,
        # joined to callers via W3C traceparent); always present so the
        # engine never branches on "is tracing on" — the no-export tracer
        # is a bounded deque append per request. `is None`, not `or`: a
        # caller's still-EMPTY tracer is falsy (len 0) and `or` would
        # silently swap in a fresh one, orphaning its export file
        self.tracer = tracer if tracer is not None else Tracer()
        # tokens -> text, for text-exact (BPE-safe) stop strings; the
        # engine stays tokenizer-agnostic — the HTTP layer injects this
        self._decode_fn = decode_fn
        # SHARDED serving (70B-class models span chips): the model threads
        # the mesh through prefill/decode/verify, params arrive pre-sharded
        # (init_params(cfg, key, mesh) / device_put with param_shardings),
        # and the KV cache shards its kv-heads axis over ``tensor`` — GSPMD
        # inserts the collectives, exactly like the training forward.
        # MoE models additionally shard expert weights over the mesh's
        # ``expert`` axis (EP x TP composes, e.g. EP4xTP2 on 2x4): the
        # expert FFN runs under shard_map (moe._expert_ffn_sharded), which
        # is also what lets int4 expert weights — a Pallas custom call
        # GSPMD cannot partition — serve sharded.
        self.mesh = mesh
        if sc.quantize_int8 and sc.quantize_int4:
            raise ValueError("quantize_int8 and quantize_int4 are mutually "
                             "exclusive — pick one weight precision")
        if sc.kv_page_tokens < 1:
            raise ValueError(f"kv_page_tokens must be >= 1, "
                             f"got {sc.kv_page_tokens}")
        if sc.kv_pool_pages < 0:
            raise ValueError(f"kv_pool_pages must be >= 0 (0 = auto), "
                             f"got {sc.kv_pool_pages}")
        if sc.serving_chunk_tokens < 0:
            raise ValueError(f"serving_chunk_tokens must be >= 0 (0 = "
                             f"monolithic), got {sc.serving_chunk_tokens}")
        # chunked prefill (ISSUE 10): prompts process in chunks of this
        # many tokens, yielding one decode step to the engine loop between
        # chunks (ChunkArbiter) — capped at max_prefill_len (the largest
        # compile bucket a chunk can ride)
        self._chunk_tokens = min(sc.serving_chunk_tokens,
                                 sc.max_prefill_len) \
            if sc.serving_chunk_tokens else 0
        self._arbiter = ChunkArbiter()
        if mesh is not None:
            from ...parallel.mesh import AXES
            ep = mesh.shape.get(AXES.EXPERT, 1)
            if ep > 1 and (not cfg.n_experts or cfg.n_experts % ep):
                raise ValueError(
                    f"expert mesh axis {ep} needs an MoE config whose "
                    f"n_experts it divides (got n_experts={cfg.n_experts})")
        self.model = LlamaModel(cfg, mesh)
        if sc.quantize_int8 or sc.quantize_int4:
            from ...models.quant import (quantize_params,
                                         quantized_logical_axes)
            # quantize on HOST (numpy pulls any device tree back), then
            # shard the int8 tree exactly like bf16 params — 70B-class
            # int8 over a slice is THE big-model production config. The
            # host leaves go straight to their SHARDED placements
            # (commit=False): a 70B stacked leaf committed whole to one
            # device first would itself exceed a v5e's HBM.
            params = quantize_params(cfg, params,
                                     bits=4 if sc.quantize_int4 else 8,
                                     commit=mesh is None)
            if mesh is not None:
                from ...parallel import param_shardings
                params = jax.device_put(
                    params,
                    param_shardings(mesh, quantized_logical_axes(
                        cfg, bits=4 if sc.quantize_int4 else 8)))
        self.params = params
        self.metrics = metrics or Metrics()
        self._describe_metrics(self.metrics)
        # the HPA scrapes from pod start — the signal must exist before traffic
        self.metrics.set_gauge("tpu_serving_queue_depth", 0)
        self.metrics.set_gauge("tpu_serving_active_slots", 0)
        self.metrics.set_gauge("tpu_serving_kv_cache_tokens", 0)
        self.metrics.set_gauge("tpu_serving_draining", 0)
        self._queue: "queue.Queue[Request]" = queue.Queue()
        # extra members carried by queued groups (submit_group): adds to
        # queue_depth so the HPA signal sees n requests, not 1.
        # += from HTTP submit threads, -= from the prefill thread: CPython
        # int read-modify-write is not atomic, so the gauge needs a lock.
        self._queued_fanout = 0
        self._fanout_lock = threading.Lock()
        # admission (max_queue_depth) is check-then-put from concurrent
        # HTTP handler threads — without a lock N racing submits could all
        # pass the check and breach the bound by N-1
        self._admit_lock = threading.Lock()
        # drain (fleet scale-down): once set, admission rejects with
        # EngineDraining while everything already accepted runs to
        # completion. Checked under _admit_lock so drain() is atomic
        # against racing submits — nothing slips in after the flag flips.
        self._draining = threading.Event()
        # requests IN TRANSIT between containers (popped from _queue but
        # still prefilling; popped from _ready but not yet slot.request):
        # invisible to queue_depth/_ready.qsize()/active_slots, so
        # ``drained`` reading only those could report empty while a live
        # request is mid-hop — and the fleet would delete the pod under
        # it. Every queue->transit transition happens under this lock, so
        # ``drained`` reads {queues, transit} atomically.
        self._transit_lock = threading.Lock()
        self._transit = 0
        # submit wake-up for the prefill loop: the transit-safe pop is a
        # get_nowait (the lock must never be held across a blocking get),
        # so without this event an idle engine would poll — up to 50ms of
        # pure wait added to every quiet-replica TTFT. set() on every put;
        # a stale set costs one extra get_nowait, never a missed request.
        self._queue_event = threading.Event()
        # prefill thread -> engine thread: (request, single cache, first token)
        self._ready: "queue.Queue[tuple[Request, Params, int]]" = \
            queue.Queue(maxsize=sc.slots)
        self._slots = [_Slot() for _ in range(sc.slots)]
        self._ring_len = self._pick_ring_len(cfg, sc)
        # -- paged decode loop eligibility (ISSUE 9; layouts lifted by
        # ISSUES 10/11, the mesh clause by ISSUE 12, adapters and
        # speculation by ISSUE 14 — the matrix is now TOTAL,
        # tensor-parallel, and multi-tenant) -------------------------------
        # the decode hot loop runs on per-slot page tables over the shared
        # arena (paged_decode_step) whenever the layout allows it: plain
        # dense K/V, int8-KV (dequant-in-kernel paged attention, scales
        # paged alongside), MLA latent arenas, the int8 LATENT combination
        # (paged_attention_mla_quant) and UNIFORM sliding windows (window
        # pages recycle through the slot's table as a fixed circular run —
        # see _decode_once_paged) all qualify; only the windowed INTERLEAVE
        # (pattern > 1, split ring/global cache) and an operator-pinned
        # ring_cache=True stay contiguous. Mesh engines page too: the
        # arena shards its kv-heads axis over ``tensor`` exactly like the
        # contiguous cache (kv_cache_pspec; MLA latents replicate — no
        # head axis) and the paged step runs under shard_map with the
        # kv-head axis local to each shard — a head count the mesh
        # doesn't divide replicates the arena instead (correct, no TP
        # memory win; see kv_arena_sharding). Speculative decoding rides
        # the multi-token paged kernels (paged_verify_step; rejection
        # rollback drops uncommitted tail pages) and multi-LoRA threads
        # adapter snapshots through the paged steps exactly like the
        # contiguous ones. Still excluded: prefix cache off (the arena IS
        # the slot storage) and — under an EXPLICIT kv_pool_pages — a
        # pool too small to hold every slot's full residency (it would
        # reject admissions under load; auto sizing below always
        # suffices).
        t = sc.kv_page_tokens
        slot_pages = -(-sc.cache_len // t)  # ceil: pages one full slot needs
        uniform_window = (cfg.sliding_window is not None
                          and cfg.sliding_window_pattern == 1)
        layout_pageable = cfg.sliding_window is None or uniform_window
        eligible = (sc.prefix_cache_enabled and t < sc.cache_len
                    and layout_pageable and sc.ring_cache is not True
                    and (sc.kv_pool_pages == 0
                         or sc.kv_pool_pages >= sc.slots * slot_pages))
        if sc.paged_decode is True and not eligible:
            raise ValueError(
                "paged_decode=True needs a pageable KV layout (plain dense, "
                "int8-KV, MLA, MLA+int8, or a UNIFORM sliding window — the "
                "windowed interleave's split ring/global cache cannot page, "
                "and ring_cache=True pins the contiguous ring), "
                "prefix_cache_enabled, "
                "kv_page_tokens < cache_len, and kv_pool_pages 0 (auto) or "
                f">= slots * ceil(cache_len / kv_page_tokens) = "
                f"{sc.slots * slot_pages}")
        # TP paged serving (ISSUE 12): how the arena sections place over
        # the mesh. "auto" shards kv-heads over ``tensor`` (kv_cache_pspec
        # — the contiguous cache's layout); a head count the mesh doesn't
        # divide falls back to a fully replicated arena so paged decode
        # never silently turns off; "replicate" pins that fallback.
        if sc.kv_arena_sharding not in ("auto", "replicate"):
            raise ValueError(f"kv_arena_sharding must be 'auto' or "
                             f"'replicate', got {sc.kv_arena_sharding!r}")
        if mesh is not None:
            from ...parallel.mesh import AXES as _AXES
            tp = mesh.shape.get(_AXES.TENSOR, 1)
        else:
            tp = 1
        self._arena_sharding = sc.kv_arena_sharding
        if (mesh is not None and self._arena_sharding == "auto"
                and not cfg.is_mla and cfg.n_kv_heads % tp != 0):
            self._arena_sharding = "replicate"
        self._paged_loop = eligible and sc.paged_decode is not False
        # paged-native prefill (ISSUE 14): chunks scatter straight into
        # pre-allocated arena pages (paged_prefill_chunk_step) — no dense
        # scratch cache, no fill_pages copy on the admission path. Rides
        # the paged loop (the pages ARE the slot storage); None = auto
        # (on whenever the loop is), False keeps the dense-scratch route,
        # True errors if the loop is off.
        if sc.paged_prefill is True and not self._paged_loop:
            raise ValueError(
                "paged_prefill=True needs the paged decode loop (a "
                "paged_decode-eligible layout with paged_decode not "
                "disabled) — the prefilled pages ARE the slot storage")
        self._paged_prefill_on = (self._paged_loop
                                  and sc.paged_prefill is not False)
        # tensor shards the paged step spans (bench/debug surface; 0 =
        # loop off, 1 = single device)
        self._paged_tp = tp if self._paged_loop else 0
        if self._paged_loop:
            # paged slots live in the arena: windowed models drop the
            # contiguous ring (prefill singles stay linear; the window's
            # memory win comes back as page RECYCLING in the slot table)
            self._ring_len = None
        # sliding-window paged ring run: a slot's table entry j >= _win_pages
        # recycles the physical page at entry j - _win_pages — by then that
        # page's positions sit entirely behind length - window, and the
        # paged kernels never read out-of-window entries. The +2 covers
        # page-boundary misalignment of the window edge plus the entry
        # being written.
        self._window = (cfg.sliding_window
                        if self._paged_loop and uniform_window else None)
        self._win_pages = ((self._window // t) + 2
                           if self._window is not None else 0)
        pageable = (sc.prefix_cache_enabled and self._ring_len is None
                    and t < sc.cache_len)
        # -- prefix cache (paged pool or dense fallback) -------------------
        # the paged pool (kv_manager.py): radix trie over page-granular
        # shared KV in one preallocated arena. Ring/mixed layouts cannot
        # page (positions ring-overwrite by design) and a disabled cache
        # skips the arena entirely — both keep register_prefix() working
        # through the dense fallback store. All prefix state — trie, pool,
        # arena reads AND writes (writes donate) — is serialized under
        # _prefix_lock; registered-prefix dedup/cap rides the same lock.
        # With the paged decode LOOP on, the engine thread's decode step
        # also reads+donates the arena — its dispatch rides the same lock,
        # so every arena-touching dispatch is serialized and always sees
        # the latest buffer handles.
        self._prefix_lock = threading.Lock()
        self._registered: list[list[int]] = []
        # in-flight /kv_prefill hops (prefill-role load: they run on
        # handler threads, never in the queue/slots — see export_handoff)
        self._handoff_lock = threading.Lock()
        self.handoff_inflight = 0
        # cumulative completed hops: heartbeats carry it so the prefill
        # pool's autoscaler can see steady short-hop traffic that the
        # sampled inflight count aliases to zero (hops last ~100ms,
        # heartbeats sample every ~2s — most samples would see idle)
        self.handoffs_total = 0
        # streaming handoff (ISSUE 10): strict-order chunk-frame assembly
        # on the decode side, built lazily (needs the arena's section
        # spec). Fed under _handoff_lock; pages land in the arena only
        # when a whole stream checks out.
        self._stream_assembler = None
        self._kv_store: Optional[PagedKVStore] = None
        self._dense_prefixes: Optional[DensePrefixStore] = None
        if pageable:
            # paged-loop auto sizing DOUBLES the arena: the decode slots
            # now live in it (one decode-cache's worth) on top of the
            # shared prefix pool (the other)
            n_pages = sc.kv_pool_pages or max(
                1, (2 * sc.slots * slot_pages) if self._paged_loop
                else sc.slots * sc.cache_len // t)
            quant = sc.quantize_kv_int8
            self._make_store = lambda: PagedKVStore(
                n_pages, t,
                lambda: self.model.init_cache(1, sc.cache_len,
                                              quantize=quant),
                mesh=mesh, arena_sharding=self._arena_sharding)
            self._kv_store = self._make_store()
        else:
            self._dense_prefixes = DensePrefixStore(
                max_adapter_variants=sc.max_prefixes)
        # contiguous batch cache — not allocated in paged-loop mode (the
        # slots' KV lives in the arena; skipping it is the memory win)
        self._cache = None if self._paged_loop \
            else self._fresh_cache(sc.slots)
        # per-slot page tables: (slots, max pages a slot can span). Rows
        # are maintained host-side (np) and shipped to device per step;
        # entries past a slot's run stay 0 — paged_attention requires
        # never-read entries to still be VALID page indices.
        self._slot_pages_max = slot_pages
        self._page_tables_np = np.zeros((sc.slots, slot_pages), np.int32)
        # hit-rate series visible from pod start (the fleet reporter and
        # dashboards divide them; zero-seeding keeps the series defined)
        self.metrics.incr("tpu_serving_prefix_cache_hits", 0)
        self.metrics.incr("tpu_serving_prefix_cache_misses", 0)
        self.metrics.incr("tpu_serving_prefix_cache_evictions", 0)
        # handoff series visible from pod start (fleet dashboards join
        # sender and receiver sides per trace)
        self.metrics.incr("tpu_serving_kv_handoff_pages", 0)
        self.metrics.incr("tpu_serving_kv_handoff_bytes", 0)
        self.metrics.incr("tpu_serving_kv_handoff_failures", 0)
        self.metrics.incr("tpu_serving_kv_handoff_stream_frames", 0)
        self.metrics.incr("tpu_serving_kv_handoff_stream_rejects", 0)
        # device-native handoff series (ISSUE 11): dashboards divide
        # device runs by total hops for the co-location hit rate, and a
        # nonzero downgrade rate flags a misdeclared placement domain
        self.metrics.incr("tpu_serving_kv_handoff_device_runs", 0)
        self.metrics.incr("tpu_serving_kv_handoff_device_bytes", 0)
        self.metrics.incr("tpu_serving_kv_handoff_device_downgrades", 0)
        # chunked-prefill series (dashboards divide interleaved steps by
        # chunks for the ITL-protection ratio)
        self.metrics.incr("tpu_serving_prefill_chunks", 0)
        self.metrics.incr("tpu_serving_chunk_interleaved_steps", 0)
        # paged-native prefill + paged speculative series (ISSUE 14):
        # dashboards read prefill_tokens against prefill_chunks for the
        # into-arena fraction, and rollback_pages against spec_proposed
        # for the rejection cost of paged drafting
        self.metrics.incr("tpu_serving_paged_prefill_tokens", 0)
        self.metrics.incr("tpu_serving_paged_speculative_steps", 0)
        self.metrics.incr("tpu_serving_paged_speculative_rollback_pages", 0)
        # KV-fabric pull series (ISSUE 16): dashboards divide pull runs
        # by directory hits for the realized pull rate; failures flag
        # transport/validation trouble — a GONE miss is NOT a failure
        # (the directory's invalidation series carries staleness)
        self.metrics.incr("tpu_serving_kv_pull_runs", 0)
        self.metrics.incr("tpu_serving_kv_pull_bytes", 0)
        self.metrics.incr("tpu_serving_kv_pull_failures", 0)
        # global prefix directory (ISSUE 16): longest-boundary keys of
        # runs this arena inserted/adopted, pending their ride on the
        # next heartbeat (ReplicaReporter drains take_prefix_publishes
        # and re-queues on a failed beat). Keyed by prefix key so
        # re-inserting one run dedups; bounded FIFO-drop-oldest — the
        # oldest pending runs are the likeliest already evicted.
        self._publish_lock = threading.Lock()
        self._prefix_publishes: "OrderedDict[str, dict]" = OrderedDict()
        # serve_main points this at the reporter's wake event so a fresh
        # publish reaches the directory on the next beat rather than one
        # interval later; invoked outside engine locks, best-effort
        self.prefix_publish_hook: Optional[Any] = None
        # flight recorder (ISSUE 17): bounded per-decode-step timeline
        # ring, served at /debug/steps and folded into serving.request
        # spans. None when off — every hot-path mark site gates on
        # `is not None`, so a disabled recorder costs one attribute load
        # per site and holds no memory
        self.recorder: Optional[FlightRecorder] = None
        if sc.flight_recorder:
            self.recorder = FlightRecorder(
                max_steps=sc.recorder_steps, max_bytes=sc.recorder_bytes,
                perf=self._perf, metrics=self.metrics,
                max_requests=max(64, 4 * sc.slots))
        # XLA recompile watchdog (ISSUE 17): ALWAYS on — its per-call
        # cost is one cache-size read, and the PR 12 flap class (a
        # cache-key change recompiling the hot loop every other step)
        # is exactly the bug that hides until production traffic
        self.watchdog = CompileWatchdog(metrics=self.metrics,
                                        tracer=self.tracer)
        # sliding-window ring pages recycled since the last step record
        self._ring_recycled = 0
        # hot-path jits ride the watchdog: fns with an alarm budget warn
        # past it; bucketed fns (budget=None — prefill-length buckets,
        # 1-row prefill vs B-row batch forms) track without alarming
        wd = self.watchdog.wrap
        self._update_page_gauges()
        # per-slot sampling state: (request seed, draws so far) -> PRNG key
        self._slot_seed = np.zeros((sc.slots,), np.uint32)
        self._slot_draws = np.zeros((sc.slots,), np.int32)
        self._row_keys = wd("row_keys", jax.jit(_row_keys), budget=None)
        # OpenAI penalties: per-slot token-occurrence counts (slots, V)
        # int32 on device, allocated lazily at the first penalized request
        # (slots x 128k-vocab x 4B = ~8MB at 16 slots — but zero cost for
        # deployments that never send penalties)
        self._tok_counts: Optional[jax.Array] = None
        # OpenAI logit_bias: per-slot (V,) additive rows, same lazy scheme
        self._logit_bias: Optional[jax.Array] = None
        # /v1/embeddings: one pooled-forward jit per prefill bucket
        self._embed_fns: dict[int, Any] = {}
        # multi-LoRA: preallocated zero stacks; slot 0 stays zero forever
        # (= base model), so adapter selection needs no conditionals
        self._adapters: Optional[dict] = None
        self._adapter_names: dict[str, int] = {}
        self._adapter_lock = threading.Lock()
        self._slot_adapter = np.zeros((sc.slots,), np.int32)
        if sc.lora_rank > 0:
            if cfg.is_mla:
                raise ValueError("multi-LoRA serving does not support MLA "
                                 "models (adapters target the wq/wk/wv "
                                 "layout; MLA has w_dkv/w_uk/w_uv)")
            e, hd, m = cfg.embed_dim, cfg.head_dim_, cfg.mlp_dim
            dims = {"wq": (e, cfg.n_heads * hd),
                    "wk": (e, cfg.n_kv_heads * hd),
                    "wv": (e, cfg.n_kv_heads * hd),
                    "wo": (cfg.n_heads * hd, e),
                    "w_gate": (e, m), "w_up": (e, m), "w_down": (m, e)}
            unknown = set(sc.lora_targets) - set(dims)
            if unknown:
                raise ValueError(f"unknown lora_targets {sorted(unknown)}")
            if cfg.n_experts and set(sc.lora_targets) & {"w_gate", "w_up",
                                                         "w_down"}:
                raise ValueError("MoE configs have no dense mlp weights to "
                                 "adapt; use attention targets")
            n = sc.max_adapters + 1
            self._adapters = {
                t: {"a": jnp.zeros((cfg.n_layers, n, dims[t][0],
                                    sc.lora_rank), cfg.dtype),
                    "b": jnp.zeros((cfg.n_layers, n, sc.lora_rank,
                                    dims[t][1]), cfg.dtype),
                    "scale": jnp.zeros((cfg.n_layers, n), jnp.float32)}
                for t in sc.lora_targets}
        self._tokens = jnp.zeros((sc.slots,), jnp.int32)
        # requests without an explicit seed draw one from this stream, so
        # an engine built with the same seed handling the same requests in
        # the same order is deterministic end to end
        self._seed_rng = np.random.default_rng(seed)
        self._seed_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, name="serving-engine",
                                        daemon=True)
        self._prefill_thread = threading.Thread(
            target=self._prefill_loop, name="serving-prefill", daemon=True)
        # the engine-loop cache is DONATED into decode/verify so XLA updates
        # the K-token slice in place instead of copying the whole
        # (L, slots, len, h, d) cache every step — on HBM that's the
        # difference between O(tokens written) and O(cache bytes) per step
        donate = (2,) if sc.donate_cache else ()
        self._decode = wd("decode", jax.jit(self.model.decode_step,
                                            donate_argnums=donate))
        # paged decode loop: arg 2 is the ARENA (donated in place of the
        # batch cache — same in-place-update economics, shared storage).
        # Mesh serving PINS out_shardings to the arena's construction
        # shardings: without the pin, GSPMD normalizes the output pspec
        # (trailing-None form differs), the donated-back arena's sharding
        # key changes after step 1, and the step compiles a second time —
        # the compile-once contract the TP tests assert. Logits and
        # lengths come back replicated (the engine pulls both to host
        # every step anyway).
        self._paged_step = None
        self._paged_verify = None
        self._paged_chunk = None
        if self._paged_loop:
            if mesh is None:
                self._paged_step = wd("paged_step", jax.jit(
                    self.model.paged_decode_step, donate_argnums=donate))
                if sc.speculate_k > 0:
                    self._paged_verify = wd("paged_verify", jax.jit(
                        self.model.paged_verify_step,
                        donate_argnums=donate))
                if self._paged_prefill_on:
                    self._paged_chunk = wd("paged_chunk", jax.jit(
                        self.model.paged_prefill_chunk_step,
                        donate_argnums=donate), budget=None)
            else:
                import functools
                from jax.sharding import NamedSharding, PartitionSpec
                repl = NamedSharding(mesh, PartitionSpec())
                arena_sh = {name: a.sharding
                            for name, a in self._kv_store.arena.items()}
                shard_kv = self._arena_sharding != "replicate"
                # a replicated arena pins replicated shard_map specs in the
                # step (sharded specs would reshard the whole arena per step)
                self._paged_step = wd("paged_step", jax.jit(
                    functools.partial(self.model.paged_decode_step,
                                      shard_kv=shard_kv),
                    donate_argnums=donate,
                    out_shardings=(repl, arena_sh, repl)))
                if sc.speculate_k > 0:
                    self._paged_verify = wd("paged_verify", jax.jit(
                        functools.partial(self.model.paged_verify_step,
                                          shard_kv=shard_kv),
                        donate_argnums=donate,
                        out_shardings=(repl, arena_sh)))
                if self._paged_prefill_on:
                    self._paged_chunk = wd("paged_chunk", jax.jit(
                        functools.partial(self.model.paged_prefill_chunk_step,
                                          shard_kv=shard_kv),
                        donate_argnums=donate,
                        out_shardings=(repl, arena_sh, repl)),
                        budget=None)
        self.metrics.set_gauge("tpu_serving_paged_decode",
                               1 if self._paged_loop else 0)
        # TP paged serving (ISSUE 12): dashboards join this to the decode
        # throughput series for the per-chip number. Always the mesh's
        # tensor degree while the loop runs — a replicated arena still
        # occupies (and should divide by) tp chips; 1 = single device,
        # 0 = loop off
        self.metrics.set_gauge("tpu_serving_paged_tp_shards",
                               self._paged_tp)
        # the contiguous loop's verify jit; the paged loop verifies
        # through _paged_verify instead (same speculative bookkeeping,
        # page tables for KV)
        self._verify = wd("verify",
                          jax.jit(self.model.verify_step,
                                  donate_argnums=donate)
                          if sc.speculate_k > 0 and not self._paged_loop
                          else None)
        # the prefill thread's per-chunk step (prefill_chunk_step: verify
        # kernel + traced index advance) is NOT donated: a prefix-cache
        # hit starts chunked appends from a gathered/stored cache, which
        # must survive for future hits
        self._chunk_step = wd("chunk_step",
                              jax.jit(self.model.prefill_chunk_step),
                              budget=None)
        if sc.speculate_k > 0:
            # zero-seed so acceptance-rate dashboards see the series from
            # pod start, not first acceptance
            self.metrics.incr("tpu_serving_spec_proposed", 0)
            self.metrics.incr("tpu_serving_spec_accepted", 0)
        self._prefill = wd("prefill", jax.jit(self.model.prefill),
                           budget=None)
        # donate the old cache so XLA updates the slot in place instead of
        # copying the whole multi-layer K/V on every admission
        self._insert = wd("insert",
                          jax.jit(LlamaModel.insert_into_slot,
                                  donate_argnums=(0,)), budget=None)
        # module-level sampler jits are SHARED across engines, and the
        # arena's write/gather jits live inside the store — the watchdog
        # POLLS their cache sizes once per decode step (_observe_step)
        # instead of wrapping: step-granular attribution, which is
        # enough to catch a flap without aliasing other engines' calls
        att = self.watchdog.attach
        att("sample_plain", _sample_plain)
        att("sample_filtered", _sample_filtered)
        att("apply_penalties", _apply_penalties)
        att("bump_counts", _bump_counts)
        if self._kv_store is not None:
            att("kv_write", getattr(self._kv_store, "_write", None))
            att("kv_gather", getattr(self._kv_store, "_gather", None))
        self.total_generated = 0
        self.last_error: Optional[str] = None
        # cost meter (ISSUE 20): per-request chip-second/dollar attribution
        # through the generations.py price table, keyed (model, pool,
        # tenant). None when off — completion pays one `is not None` test
        # and nothing else (the flight-recorder bargain). One call per
        # COMPLETED request keeps it far under the 2% hot-loop bar.
        self.costmeter: Optional[CostMeter] = None
        if sc.cost_meter:
            self.costmeter = CostMeter(
                self.metrics, model=cfg.name,
                accelerator=os.environ.get("TPU_ACCELERATOR_TYPE", ""),
                chips=int(mesh.devices.size) if mesh is not None else 1,
                pool=os.environ.get("TPU_SERVING_POOL", ""),
                clock=self._perf)

    @staticmethod
    def _describe_metrics(m: Metrics):
        """HELP/TYPE for every serving metric (tests/test_metrics_lint.py
        fails any call site without a matching describe — the README
        catalogue stays honest as metrics accumulate)."""
        m.describe("tpu_serving_queue_depth",
                   "requests waiting for a decode slot (HPA signal)")
        m.describe("tpu_serving_active_slots",
                   "decode slots currently holding a live request")
        m.describe("tpu_serving_kv_cache_tokens",
                   "tokens (prompt + generated) held in active KV slots")
        m.describe("tpu_serving_admitted",
                   "requests admitted into a decode slot")
        m.describe("tpu_serving_admission_rejected",
                   "submits rejected at max_queue_depth (mapped to HTTP 429)")
        m.describe("tpu_serving_drain_rejected",
                   "submits rejected while draining (mapped to HTTP 503)")
        m.describe("tpu_serving_draining",
                   "1 while the engine is draining (fleet scale-down)")
        m.describe("tpu_serving_cancelled",
                   "requests cancelled by their caller (timeout/disconnect)")
        m.describe("tpu_serving_stream_cancelled",
                   "streamed requests cancelled by a failing token callback")
        m.describe("tpu_serving_decode_steps",
                   "batched decode/verify steps executed by the engine loop")
        m.describe("tpu_serving_engine_errors",
                   "engine-loop steps that raised (in-flight requests failed)")
        m.describe("tpu_serving_prefill_errors",
                   "prefills that raised (poisoned prompt; request failed)")
        m.describe("tpu_serving_prefix_hits",
                   "prompts that skipped a registered prefix's prefill")
        m.describe("tpu_serving_prefix_adapter_fills",
                   "lazy per-adapter prefix variants computed on first use")
        m.describe("tpu_serving_prefix_cache_hits",
                   "prompts that reused >= 1 shared KV page (prefill skipped "
                   "for the matched span)")
        m.describe("tpu_serving_prefix_cache_misses",
                   "prompts the prefix trie matched nothing for (full "
                   "prefill)")
        m.describe("tpu_serving_prefix_cache_evictions",
                   "KV pages evicted from the prefix trie (LRU leaves) to "
                   "make room")
        m.describe("tpu_serving_kv_pages_total",
                   "KV pages in the preallocated paged-prefix arena")
        m.describe("tpu_serving_kv_pages_free",
                   "KV pages on the free list (unreferenced)")
        m.describe("tpu_serving_kv_pages_shared",
                   "KV pages serving more than one cached sequence "
                   "(trie-interior or multiply-referenced: the dedup win)")
        m.describe("tpu_serving_paged_decode",
                   "1 when the decode hot loop runs on per-slot page "
                   "tables over the shared arena (zero-copy prefix/"
                   "handoff adoption), 0 on the contiguous slot cache")
        m.describe("tpu_serving_paged_tp_shards",
                   "tensor-parallel shards the paged decode step runs "
                   "over (shard_mapped arena; 1 = single device, 0 = "
                   "paged loop off)")
        m.describe("tpu_serving_kv_handoff_pages",
                   "KV pages moved by prefill->decode handoffs (sender "
                   "counts serialized pages, receiver counts adopted)")
        m.describe("tpu_serving_kv_handoff_bytes",
                   "serialized KV bytes moved by prefill->decode handoffs")
        m.describe("tpu_serving_kv_handoff_failures",
                   "KV handoffs that failed (serialization, validation, "
                   "or adoption) — the router falls back to a full "
                   "prefill on the target")
        m.describe("tpu_serving_kv_handoff_stream_frames",
                   "streamed-handoff chunk frames moved (sender counts "
                   "pushed frames, receiver counts accepted)")
        m.describe("tpu_serving_kv_handoff_stream_rejects",
                   "chunk frames rejected on the decode side (torn/"
                   "duplicate/reordered/stale stream) — the whole stream "
                   "drops, nothing is adopted")
        m.describe("tpu_serving_kv_handoff_device_runs",
                   "KV page runs moved DEVICE-NATIVE (arena-to-arena, "
                   "zero host copies) between co-located replicas — "
                   "sender counts exports, receiver counts adoptions")
        m.describe("tpu_serving_kv_handoff_device_bytes",
                   "device-array bytes moved by device-native handoffs "
                   "(payload never touches numpy or HTTP)")
        m.describe("tpu_serving_kv_handoff_device_downgrades",
                   "device-path hops that fell back to the wire codec "
                   "(bus miss, domain mismatch, geometry/adoption "
                   "failure) — the ladder is device -> wire -> unified")
        m.describe("tpu_serving_kv_pull_runs",
                   "KV page runs moved by directory-planned pulls "
                   "(owner counts exports, cold replica counts "
                   "adoptions)")
        m.describe("tpu_serving_kv_pull_bytes",
                   "bytes moved by directory pulls (serialized blob "
                   "bytes on the shm/wire rungs, device-array bytes on "
                   "the device rung)")
        m.describe("tpu_serving_kv_pull_failures",
                   "pull hops that failed in transport or validation — "
                   "a GONE miss (owner evicted the pages since publish) "
                   "is counted by the directory's invalidations series "
                   "instead, not here")
        m.describe("tpu_serving_prefill_chunks",
                   "prompt chunks processed by chunked prefill "
                   "(serving_chunk_tokens > 0)")
        m.describe("tpu_serving_chunk_interleaved_steps",
                   "decode steps the engine ran BETWEEN prefill chunks "
                   "(the co-resident ITL protection chunking exists for)")
        m.describe("tpu_serving_spec_proposed",
                   "speculative draft tokens proposed")
        m.describe("tpu_serving_spec_accepted",
                   "speculative draft tokens accepted (committed for free)")
        m.describe("tpu_serving_paged_prefill_tokens",
                   "prompt tokens prefilled STRAIGHT INTO arena pages "
                   "(paged-native chunks — no dense scratch cache, no "
                   "fill_pages copy on the admission path)")
        m.describe("tpu_serving_paged_speculative_steps",
                   "speculative verify steps run on the paged loop "
                   "(multi-token paged kernels over per-slot page tables)")
        m.describe("tpu_serving_paged_speculative_rollback_pages",
                   "tail pages dropped by speculative rejection rollback "
                   "on the paged loop (uncommitted drafts' pages returned "
                   "to the pool)")
        m.describe("tpu_serving_request_latency_seconds",
                   "submit -> completion, whole request")
        m.describe("tpu_serving_ttft_seconds",
                   "submit -> first generated token (time to first token)",
                   buckets=TTFT_BUCKETS)
        m.describe("tpu_serving_inter_token_seconds",
                   "gap between consecutive streamed tokens of one request",
                   buckets=ITL_BUCKETS)
        m.describe("tpu_serving_queue_wait_seconds",
                   "submit -> prefill start (admission queue wait)",
                   buckets=TTFT_BUCKETS)
        m.describe("tpu_serving_batch_utilization",
                   "filled slots / max slots per decode step",
                   buckets=UTIL_BUCKETS)
        m.describe("tpu_serving_step_wall_seconds",
                   "decode-step wall time (flight recorder; the four "
                   "phase histograms below sum to it per step)",
                   buckets=STEP_BUCKETS)
        m.describe("tpu_serving_step_schedule_seconds",
                   "step phase: host-side batch assembly — slot-table "
                   "growth, lengths/page-table staging, draft proposals",
                   buckets=STEP_BUCKETS)
        m.describe("tpu_serving_step_kernel_seconds",
                   "step phase: device DISPATCH of the decode/verify jit "
                   "(async — materialization lands in the sample phase)",
                   buckets=STEP_BUCKETS)
        m.describe("tpu_serving_step_sample_seconds",
                   "step phase: logits materialization + per-slot "
                   "sampling (temperature/top-k/top-p, penalties, "
                   "logprobs)",
                   buckets=STEP_BUCKETS)
        m.describe("tpu_serving_step_commit_seconds",
                   "step phase: host-side token commit — stream "
                   "emission, stop checks, slot bookkeeping, rollback",
                   buckets=STEP_BUCKETS)
        m.describe("tpu_serving_step_tokens",
                   "tokens committed per decode step (speculative steps "
                   "commit several per slot)",
                   buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0))
        m.describe("tpu_serving_step_ring_records",
                   "records currently held by the flight-recorder ring")
        m.describe("tpu_serving_step_ring_bytes",
                   "serialized bytes held by the flight-recorder ring "
                   "(hard-bounded by recorder_bytes)")
        m.describe("tpu_serving_recompiles",
                   "hot-path jit compiles BEYOND the first, per alarmed "
                   "function — any rise is a cache-key flap (changed "
                   "avals, shardings, or donation pattern recompiling "
                   "the hot loop)")
        # zero-seed every heartbeat-merged counter (ISSUE 20): the fleet
        # reporter reads these cumulative and the registry tier
        # differences them per beat (SLO windows, scheduler matrix, the
        # metrics merge) — a series that first appears mid-flight reads
        # as a restart to the guards. graftlint's merged-counter rule
        # pins each name to a seed site like this one.
        m.incr("tpu_serving_admitted", 0)
        m.incr("tpu_serving_decode_steps", 0)
        m.incr("tpu_serving_engine_errors", 0)
        m.incr("tpu_serving_prefill_errors", 0)

    def _fresh_cache(self, batch: int) -> Params:
        """One construction path for every cache this engine makes (the
        batch cache, prefill singles, and the post-crash rebuild).

        Mesh serving: the cache is built DIRECTLY under its sharding
        (jit + out_shardings) — allocating the full (L, slots, len, h, d)
        tree on one device and resharding after would OOM at construction
        for exactly the 70B-class models sharding exists for. K/V
        sections shard their kv-heads axis over ``tensor`` (the attention
        compute layout); bookkeeping (index/abs_pos) replicates."""
        def build() -> Params:
            if self._ring_len is not None:
                if self.cfg.sliding_window_pattern > 1:
                    # Gemma-2/3: ring for local sublayers, full for global
                    return self.model.init_mixed_cache(
                        batch, self.sc.cache_len, self._ring_len,
                        quantize=self.sc.quantize_kv_int8)
                return self.model.init_ring_cache(
                    batch, self._ring_len, quantize=self.sc.quantize_kv_int8)
            return self.model.init_cache(
                batch, self.sc.cache_len, quantize=self.sc.quantize_kv_int8)

        if self.mesh is None:
            return build()
        import jax
        from jax.sharding import NamedSharding

        shapes = jax.eval_shape(build)
        shardings = {name: NamedSharding(self.mesh,
                                         kv_cache_pspec(name, sd.ndim))
                     for name, sd in shapes.items()}
        return jax.jit(build, out_shardings=shardings)()

    @staticmethod
    def _pick_ring_len(cfg: LlamaConfig, sc: ServingConfig) -> Optional[int]:
        """Physical ring size for the windowed layers, or None for a plain
        linear cache. The slack term is the most tokens one prefill/verify
        call can write — the ring invariant (init_ring_cache docstring) that
        keeps every in-window entry alive across chunked prefill and
        speculative rejections. Uniform-window models (Mistral) ring every
        layer; interleave models (Gemma-2/3) get the SPLIT cache — rings
        for local sublayers, full length for global ones — both compose
        with the int8 KV cache (int8 shrinks the read traffic, the ring
        shrinks the position axis; orthogonal wins)."""
        windowed = cfg.sliding_window is not None
        if sc.ring_cache is False or (sc.ring_cache is None and not windowed):
            return None
        if not windowed:
            raise ValueError("ring_cache=True needs a model with a "
                             "sliding window")
        # effective max in-flight tokens of ONE cache-writing call: with
        # chunked prefill on, every call (head included) writes one chunk
        # padded to its pow2 compile bucket (capped at max_prefill_len) —
        # and a serving_chunk_tokens ABOVE max_prefill_len writes the raw
        # chunk, which the bucket cap cannot shrink (the old
        # max(max_prefill_len, ...) slack UNDER-reserved there, letting a
        # big chunk ring-overwrite live in-window entries). Without
        # chunking the head is a full max_prefill_len bucket.
        if sc.serving_chunk_tokens:
            b = 16
            while b < sc.serving_chunk_tokens:
                b *= 2
            eff = max(sc.serving_chunk_tokens, min(b, sc.max_prefill_len))
        else:
            eff = sc.max_prefill_len
        slack = max(eff, sc.speculate_k + 1)
        ring = -(-(cfg.sliding_window + slack) // 128) * 128
        if sc.ring_cache is None and ring >= sc.cache_len:
            return None  # no memory win — stay linear
        return ring

    # -- public API ------------------------------------------------------------

    def start(self) -> "ServingEngine":
        self._thread.start()
        self._prefill_thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)
        self._prefill_thread.join(timeout=10)

    def submit(self, prompt: list[int], max_new_tokens: Optional[int] = None,
               temperature: Optional[float] = None,
               top_k: int = 0, top_p: float = 1.0,
               presence_penalty: float = 0.0, frequency_penalty: float = 0.0,
               logit_bias: Optional[dict] = None,
               stop: Optional[list] = None,
               stop_text: Optional[list] = None, logprobs: bool = False,
               adapter: str = "", seed: Optional[int] = None,
               on_token=None, trace_id: str = "", parent_span: str = "",
               span_id: str = "", tenant: str = "",
               _build_only: bool = False):
        """Enqueue a generation request; resolves to {tokens, latency_s, rid}
        (+ per-token "logprobs" when requested). ``on_token(tok)`` streams
        each generated token id as it decodes. ``top_k``/``top_p`` filter
        the sampling distribution per request (active only when
        temperature > 0). ``stop``: list of token sequences; generation
        ends when the output tail equals one. ``seed`` makes sampling
        reproducible for this request regardless of slot placement or
        co-resident traffic."""
        if not prompt:
            f: Future = Future()
            f.set_exception(ValueError("empty prompt"))
            return f
        if len(prompt) > self.sc.cache_len - 1:
            # prompts longer than one prefill bucket run CHUNKED (the
            # verify kernel appends each chunk to the cache), so the real
            # ceiling is the per-slot KV budget minus one generated token
            f = Future()
            f.set_exception(ValueError(
                f"prompt length {len(prompt)} > cache budget "
                f"{self.sc.cache_len - 1}"))
            return f
        if max_new_tokens is None:
            max_new_tokens = self.sc.max_new_tokens
        if not isinstance(max_new_tokens, int) or isinstance(max_new_tokens, bool) \
                or max_new_tokens < 1:
            f = Future()
            f.set_exception(ValueError(
                f"max_new_tokens must be a positive int, got {max_new_tokens!r}"))
            return f
        if temperature is None:
            temperature = self.sc.temperature
        if not isinstance(temperature, (int, float)) \
                or isinstance(temperature, bool) or temperature < 0.0:
            f = Future()
            f.set_exception(ValueError(
                f"temperature must be a non-negative number, got {temperature!r}"))
            return f
        if not isinstance(top_k, int) or isinstance(top_k, bool) or top_k < 0:
            f = Future()
            f.set_exception(ValueError(
                f"top_k must be a non-negative int, got {top_k!r}"))
            return f
        if not isinstance(top_p, (int, float)) or isinstance(top_p, bool) \
                or not 0.0 < top_p <= 1.0:
            f = Future()
            f.set_exception(ValueError(
                f"top_p must be in (0, 1], got {top_p!r}"))
            return f
        for pname, pv in (("presence_penalty", presence_penalty),
                          ("frequency_penalty", frequency_penalty)):
            if not isinstance(pv, (int, float)) or isinstance(pv, bool) \
                    or not -2.0 <= pv <= 2.0:
                f = Future()
                f.set_exception(ValueError(
                    f"{pname} must be in [-2, 2], got {pv!r}"))
                return f
        if logit_bias:
            try:
                logit_bias = {int(t): float(bias)
                              for t, bias in logit_bias.items()}
            except (TypeError, ValueError, AttributeError):
                f = Future()
                f.set_exception(ValueError(
                    "logit_bias must map token ids to numbers"))
                return f
            if not all(0 <= t < self.cfg.vocab_size
                       and -100.0 <= bias <= 100.0
                       for t, bias in logit_bias.items()):
                f = Future()
                f.set_exception(ValueError(
                    "logit_bias keys must be valid token ids and biases "
                    "in [-100, 100]"))
                return f
        stop = stop or []
        if not (isinstance(stop, list) and all(
                isinstance(s, list) and s
                and all(isinstance(t, int) for t in s) for s in stop)):
            f = Future()
            f.set_exception(ValueError(
                "stop must be a list of non-empty token lists"))
            return f
        stop_text = stop_text or []
        if not (isinstance(stop_text, list) and all(
                isinstance(s, str) and s for s in stop_text)):
            f = Future()
            f.set_exception(ValueError(
                "stop_text must be a list of non-empty strings"))
            return f
        if stop_text and self._decode_fn is None:
            f = Future()
            f.set_exception(ValueError(
                "stop_text needs an engine decode_fn (tokenizer)"))
            return f
        adapter_id = 0
        if adapter:
            with self._adapter_lock:
                aid = self._adapter_names.get(adapter)
            if aid is None:
                f = Future()
                f.set_exception(ValueError(f"unknown adapter {adapter!r}"))
                return f
            adapter_id = aid
        if seed is None:
            with self._seed_lock:
                seed = int(self._seed_rng.integers(0, 2 ** 32))
        elif not isinstance(seed, int) or isinstance(seed, bool):
            f = Future()
            f.set_exception(ValueError(f"seed must be an int, got {seed!r}"))
            return f
        req = Request(prompt=list(prompt),
                      max_new_tokens=min(max_new_tokens,
                                         self.sc.cache_len - len(prompt)),
                      rid=uuid.uuid4().hex[:8], future=Future(),
                      submitted_at=self._perf(),
                      temperature=float(temperature),
                      top_k=top_k, top_p=float(top_p),
                      presence_penalty=float(presence_penalty),
                      frequency_penalty=float(frequency_penalty),
                      logit_bias=logit_bias or None,
                      stop=[list(s) for s in stop],
                      stop_texts=list(stop_text), logprobs=bool(logprobs),
                      adapter_id=adapter_id, seed=seed & 0xFFFFFFFF,
                      on_token=on_token, trace_id=str(trace_id or ""),
                      span_id=str(span_id or ""),
                      parent_span_id=str(parent_span or ""),
                      tenant=str(tenant or ""))
        if _build_only:
            return req
        with self._admit_lock:  # atomic check+put: racing submits must not
            # breach the bound by one each
            if self._draining.is_set():
                self.metrics.incr("tpu_serving_drain_rejected")
                f = Future()
                f.set_exception(EngineDraining(
                    "engine is draining; submit to another replica"))
                return f
            if (self.sc.max_queue_depth
                    and self.queue_depth >= self.sc.max_queue_depth):
                # admission bound (bounded-latency contract): the client
                # gets an immediate typed rejection, not an unbounded wait
                self.metrics.incr("tpu_serving_admission_rejected")
                f = Future()
                f.set_exception(EngineOverloaded(
                    f"queue depth {self.queue_depth} at max_queue_depth "
                    f"{self.sc.max_queue_depth}; retry later"))
                return f
            self._queue.put(req)
            self._queue_event.set()
        self.metrics.set_gauge("tpu_serving_queue_depth", self.queue_depth)
        return req.future

    def submit_group(self, prompt: list[int], n: int,
                     seed: Optional[int] = None, **kw) -> list[Future]:
        """n co-submitted requests over the IDENTICAL prompt (OpenAI n>1):
        the prompt prefills ONCE and the immutable cache fans out to all
        members, so time-to-first-token is ~1x, not ~n-x. ``seed`` offsets
        per member so sampled choices differ; kw matches submit()."""
        if not isinstance(n, int) or isinstance(n, bool) or n < 1:
            f: Future = Future()
            f.set_exception(ValueError(f"n must be a positive int, got {n!r}"))
            return [f]
        # member 0 carries ALL the validation — members differ only in the
        # seed offset, and submit's seed type check runs before any
        # arithmetic can TypeError (member 0 gets the raw seed)
        first = self.submit(prompt, seed=seed, _build_only=True, **kw)
        if isinstance(first, Future):
            exc = first.exception()
            fs = [first]
            for _ in range(n - 1):
                f = Future()
                f.set_exception(exc)
                fs.append(f)
            return fs
        reqs = [first]
        kw.pop("span_id", None)  # the caller's root span id names member 0
        # only; siblings mint their own (same trace_id still groups them)
        for i in range(1, n):
            reqs.append(self.submit(prompt,
                                    seed=None if seed is None else seed + i,
                                    _build_only=True, **kw))
        head = reqs[0]
        head.fanout = reqs[1:]
        with self._admit_lock:  # atomic check+put, like submit()
            if self._draining.is_set():
                self.metrics.incr("tpu_serving_drain_rejected")
                exc = EngineDraining(
                    "engine is draining; submit to another replica")
                fs = []
                for _ in range(n):
                    f = Future()
                    f.set_exception(exc)
                    fs.append(f)
                return fs
            if self.sc.max_queue_depth and (
                    self.queue_depth + n > self.sc.max_queue_depth):
                # group admission counts ALL members against the bound
                self.metrics.incr("tpu_serving_admission_rejected")
                exc = EngineOverloaded(
                    f"queue depth {self.queue_depth} + group of {n} exceeds "
                    f"max_queue_depth {self.sc.max_queue_depth}; retry later")
                fs = []
                for _ in range(n):
                    f = Future()
                    f.set_exception(exc)
                    fs.append(f)
                return fs
            with self._fanout_lock:
                self._queued_fanout += len(head.fanout)
            self._queue.put(head)
            self._queue_event.set()
        self.metrics.set_gauge("tpu_serving_queue_depth", self.queue_depth)
        return [r.future for r in reqs]

    def drain(self):
        """Begin a graceful drain (fleet scale-down contract): stop
        admitting new requests (submits resolve to EngineDraining ->
        HTTP 503), finish everything in flight or already queued.
        Idempotent. ``drained`` flips True when the last request
        completes; the fleet reporter then deregisters and the autoscaler
        deletes the pod — no request is ever dropped by a scale-down."""
        if not self._draining.is_set():
            log.info("serving engine draining: %d queued, %d active",
                     self.queue_depth, self.active_slots)
        with self._admit_lock:  # atomic vs racing submits (see submit())
            self._draining.set()
        self.metrics.set_gauge("tpu_serving_draining", 1)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    @property
    def drained(self) -> bool:
        """Drain complete: nothing queued, in transit, prefilled, or
        decoding. The transit count closes the mid-hop windows: a request
        popped from a queue is counted as transit BEFORE the pop (same
        lock), and a slot's ``request`` is set before its transit count
        drops — so at ``transit == 0 and ready == 0``, anything admitted
        is visible in active_slots."""
        if not self._draining.is_set():
            return False
        with self._transit_lock:
            if self._transit or self.queue_depth or self._ready.qsize():
                return False
        return self.active_slots == 0

    @property
    def alive(self) -> bool:
        """Engine-thread liveness (k8s liveness probes should gate on this)."""
        return self._thread.is_alive()

    @property
    def adapter_names(self) -> tuple[str, ...]:
        with self._adapter_lock:
            return tuple(self._adapter_names)

    @property
    def multi_lora_enabled(self) -> bool:
        return self._adapters is not None

    @property
    def queue_depth(self) -> int:
        # counts every pending request: an n-member group is one queue
        # entry but n requests (the HPA gauge must not undercount); the
        # fanout counter is += / -= under _fanout_lock, so read it there too
        with self._fanout_lock:
            fanout = self._queued_fanout
        return self._queue.qsize() + fanout

    @property
    def active_slots(self) -> int:
        return sum(1 for s in self._slots if s.request is not None)

    def prefix_cache_stats(self) -> dict:
        """Pool/trie occupancy + registered count — the kv_pages gauges'
        source of truth, also consumed by tests and the fleet reporter."""
        with self._prefix_lock:
            if self._kv_store is not None:
                out = self._kv_store.stats()
            else:
                out = {"pages_total": 0, "pages_free": 0, "pages_shared": 0,
                       "nodes": 0, "pinned": 0, "adapters": []}
                if self._dense_prefixes is not None:
                    out["dense_entries"] = len(self._dense_prefixes)
            out["registered"] = len(self._registered)
            out["page_tokens"] = self.sc.kv_page_tokens
            if self._kv_store is not None:
                out["page_bytes"] = self._kv_store.page_bytes
            return out

    def debug_snapshot(self) -> dict:
        """Statusz-style snapshot for /debug/engine: in-flight slots with
        per-request age/token counts, queue depths, and prefix/adapter
        occupancy. Read from HTTP handler threads while the engine mutates —
        each field is a single GIL-atomic read, so a snapshot may straddle a
        step (debug surface, not an invariant)."""
        now = self._perf()
        slots = []
        for i, s in enumerate(self._slots):
            r = s.request
            if r is None:
                slots.append({"slot": i, "state": "free"})
                continue
            entry = {
                "slot": i, "state": "decoding", "rid": r.rid,
                "trace_id": r.trace_id or None,
                "age_s": round(now - r.submitted_at, 4),
                "prompt_tokens": len(r.prompt),
                "generated_tokens": len(s.generated),
                "remaining_tokens": s.remaining,
                "adapter_id": r.adapter_id,
            }
            if self._paged_loop:
                entry["pages"] = len(s.pages)
            slots.append(entry)
        with self._prefix_lock:
            if self._dense_prefixes is not None:
                prefixes = self._dense_prefixes.snapshot()
            else:
                prefixes = [{"tokens": len(t)} for t in self._registered]
        kv_tokens = sum(s.get("prompt_tokens", 0) + s.get("generated_tokens", 0)
                        for s in slots)
        with self._handoff_lock:
            handoff_inflight = self.handoff_inflight
            handoffs_total = self.handoffs_total
        return {
            # /debug/engine wire shape; tools warn on unknown versions
            "schema_version": 1,
            "model": self.cfg.name,
            "alive": self.alive,
            "draining": self.draining,
            "drained": self.drained,
            "slots": slots,
            "active_slots": sum(1 for s in slots if s["state"] != "free"),
            "max_slots": self.sc.slots,
            "queue_depth": self.queue_depth,
            "ready_queue": self._ready.qsize(),
            # requests mid-hop between queues/slots (see drained): the
            # fleet reporter folds this into its queue_depth so a remote
            # drain-progress check can't see "empty" during a hop
            "in_transit": self._transit,
            "handoff_inflight": handoff_inflight,
            "handoffs_total": handoffs_total,
            "kv_cache_tokens": kv_tokens,
            "cache_len": self.sc.cache_len,
            "paged_decode": self._paged_loop,
            "paged_prefill": self._paged_prefill_on,
            "paged_tp_shards": self._paged_tp,
            "kv_arena_sharding": self._arena_sharding,
            "prefixes": prefixes,
            "max_prefixes": self.sc.max_prefixes,
            "prefix_cache": self.prefix_cache_stats(),
            "adapters": list(self.adapter_names),
            "total_generated": self.total_generated,
            "last_error": self.last_error,
        }

    # -- engine loop -----------------------------------------------------------

    def _loop(self):
        while not self._stop.is_set():
            try:
                admitted = self._admit()
                if self.active_slots == 0:
                    if not admitted:
                        self._stop.wait(0.002)
                    continue
                self._decode_once()
                # wake chunked prefills waiting their one-step turn
                # (ChunkArbiter contract; a no-waiter notify is ~free)
                self._arbiter.decode_step_done()
            except Exception as exc:  # noqa: BLE001 — engine must survive bad steps
                # Fail everything in flight so no caller hangs, then keep
                # serving: one poisoned request must not be a permanent outage.
                log.exception("serving engine step failed; failing in-flight "
                              "requests and continuing")
                self.last_error = f"{type(exc).__name__}: {exc}"
                self.metrics.incr("tpu_serving_engine_errors")
                for slot in self._slots:
                    req, slot.request = slot.request, None
                    if req is not None:
                        _fail_future(req.future, exc)
                drained_fanout = 0
                while True:
                    try:
                        req = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    members = [req] + list(req.fanout or [])
                    drained_fanout += len(members) - 1
                    for member in members:
                        _fail_future(member.future, exc)
                while True:
                    try:
                        req, *_ = self._ready.get_nowait()
                    except queue.Empty:
                        break
                    _fail_future(req.future, exc)
                # subtract only groups actually drained: a submit thread may
                # have counted its group but not queued it yet — zeroing here
                # would double-subtract when the dispatcher later pops it,
                # driving the HPA gauge permanently negative
                with self._fanout_lock:
                    self._queued_fanout -= drained_fanout
                self.metrics.set_gauge("tpu_serving_queue_depth",
                                       self.queue_depth)
                self.metrics.set_gauge("tpu_serving_active_slots", 0)
                self.metrics.set_gauge("tpu_serving_kv_cache_tokens", 0)
                # LAST, after every in-flight future is failed: the crashed
                # step may have DONATED the cache buffers before raising, so
                # decode needs fresh ones. If even this allocation fails
                # (e.g. the same HBM OOM), the engine thread dies — but no
                # caller is left hanging, and `alive` flips for the probes.
                if self._paged_loop:
                    # the crashed step may have donated the ARENA: rebuild
                    # the whole store (fresh arena + empty trie + full free
                    # list) and drop every slot's page state. Registered
                    # prefixes survive in _registered (dedup keeps working)
                    # but their pinned KV is gone — the next prompt re-
                    # prefills and re-caches it, a latency blip, not a
                    # correctness loss.
                    for slot in self._slots:
                        slot.pages = []
                        slot.kv_len = 0
                        slot.table_len = 0
                    self._page_tables_np[:] = 0
                    with self._prefix_lock:
                        self._kv_store = self._make_store()
                else:
                    self._cache = self._fresh_cache(self.sc.slots)
                self._tokens = jnp.zeros((self.sc.slots,), jnp.int32)
                self._slot_adapter[:] = 0

    def _padded(self, toks: list[int]) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Zero-pad to the compile bucket; returns (tokens (1, bucket),
        true_len (1,)) — one policy for the head and every chunk."""
        bucket = self._bucket_len(len(toks))
        arr = jnp.asarray([toks + [0] * (bucket - len(toks))], jnp.int32)
        return arr, jnp.asarray([len(toks)], jnp.int32)

    def _bucket_len(self, n: int) -> int:
        b = 16
        while b < n:
            b *= 2
        return min(b, self.sc.max_prefill_len)

    def _append_chunks(self, single: Params, toks: list[int], last_logits,
                       adapter_id: int = 0, adapters: Optional[dict] = None,
                       on_chunk=None, done: int = 0):
        """Append ``toks`` to a single-request cache chunk by chunk
        through ``prefill_chunk_step`` (each chunk's padding KV lands
        beyond the committed index, so it is never attended and is later
        overwritten — the decode-path invariant). Chunk size is
        ``serving_chunk_tokens`` when chunked prefill is on (yielding one
        decode step to the engine loop between chunks via the
        ChunkArbiter — the co-resident ITL protection), else
        ``max_prefill_len``. Returns (logits, cache).

        ``adapters`` is the caller's SNAPSHOT of the adapter tree, so one
        request never mixes weights across a concurrent re-registration.
        ``on_chunk(single, done_total)`` fires after every chunk with the
        cumulative token count committed (``done`` counts tokens already
        in the cache before this call) — the streaming-handoff hook."""
        ad_ids = self._single_ad_ids(adapter_id)
        step = self._chunk_tokens or self.sc.max_prefill_len
        for start in range(0, len(toks), step):
            chunk = toks[start:start + step]
            ctoks, true_len = self._padded(chunk)
            last_logits, single = self._chunk_step(
                self.params, ctoks, single, true_len, adapters, ad_ids)
            done += len(chunk)
            # on_chunk BEFORE the yield: the streaming hook hands this
            # chunk's pages to the sender, whose push should ride under
            # the decode step (and the next chunk) — yielding first would
            # hold every frame back one ITL and erode the very overlap
            # the stream exists for
            if on_chunk is not None:
                on_chunk(single, done)
            if self._chunk_tokens:
                self.metrics.incr("tpu_serving_prefill_chunks")
                if start + step < len(toks):
                    # between chunks only — the final chunk's successor is
                    # this request's own first decode step
                    ran = self._arbiter.yield_for_decode(
                        lambda: self.active_slots > 0)
                    if ran:
                        self.metrics.incr(
                            "tpu_serving_chunk_interleaved_steps", ran)
                        if self.recorder is not None:
                            self.recorder.event("chunk_interleave",
                                                steps=ran)
        return last_logits, single

    def _single_ad_ids(self, adapter_id: int):
        if self._adapters is None:
            return None
        return jnp.asarray([adapter_id], jnp.int32)

    def _prefill_raw(self, tokens: list[int], adapter_id: int,
                     adapters, on_chunk=None) -> tuple[Any, Params]:
        """Prefill WITHOUT prefix-cache consultation: head through the
        bucketed prefill jit, remainder chunked through the verify
        kernel. With chunked prefill on, the head is one chunk too — even
        the first dispatch stays small enough to interleave behind."""
        single = self._fresh_cache(1)
        head = tokens[:self._chunk_tokens or self.sc.max_prefill_len]
        prompt, true_len = self._padded(head)
        last_logits, single = self._prefill(
            self.params, prompt, single, true_len, adapters,
            self._single_ad_ids(adapter_id))
        if on_chunk is not None:
            on_chunk(single, len(head))
        if self._chunk_tokens:
            self.metrics.incr("tpu_serving_prefill_chunks")
            if len(tokens) > len(head):
                ran = self._arbiter.yield_for_decode(
                    lambda: self.active_slots > 0)
                if ran:
                    self.metrics.incr("tpu_serving_chunk_interleaved_steps",
                                      ran)
                    if self.recorder is not None:
                        self.recorder.event("chunk_interleave", steps=ran)
        return self._append_chunks(single, tokens[len(head):], last_logits,
                                   adapter_id, adapters, on_chunk=on_chunk,
                                   done=len(head))

    def embed(self, tokens: list[int]) -> list[float]:
        """Mean-pooled final-norm hidden state of the prompt — the
        /v1/embeddings backing. Reuses the prefill compile buckets (one
        jit per bucket; the padding positions are masked out of the mean,
        so the same prompt embeds identically in any bucket). Runs on the
        caller's thread: device work serializes with decode steps, which
        is the right trade for a secondary endpoint (no queueing machinery
        for a forward pass)."""
        if not tokens:
            raise ValueError("empty input")
        if len(tokens) > self.sc.max_prefill_len:
            # erroring beats silent truncation (OpenAI rejects over-long
            # embedding inputs too): usage/billing must reflect what was
            # actually embedded
            raise ValueError(
                f"input length {len(tokens)} exceeds this server's "
                f"embedding context ({self.sc.max_prefill_len} tokens)")
        if not all(isinstance(t, int) and 0 <= t < self.cfg.vocab_size
                   for t in tokens):
            raise ValueError("input token ids must be within the vocabulary")
        bucket = self._bucket_len(len(tokens))
        fn = self._embed_fns.get(bucket)
        if fn is None:
            model = self.model

            def pooled(params, toks, n):
                hidden = model.forward(params, toks, return_hidden=True)
                # pool in f32: bf16 accumulation over hundreds of positions
                # loses ~1e-2 relative precision, and n itself may not be
                # bf16-representable
                h32 = hidden.astype(jnp.float32)
                mask = (jnp.arange(h32.shape[1]) < n)[None, :, None]
                s = jnp.sum(h32 * mask, axis=1)
                return (s / n.astype(jnp.float32))[0]

            fn = self._embed_fns[bucket] = self.watchdog.wrap(
                f"embed_{bucket}", jax.jit(pooled))
        arr, n = self._padded(tokens)
        return [float(x) for x in np.asarray(fn(self.params, arr, n[0]))]

    # -- prefix cache ----------------------------------------------------------

    def _covers_registered(self, tokens: list[int]) -> bool:
        """Does this prompt start with some register_prefix() prefix? The
        registered list is small (max_prefixes) and host-side, so this is
        the cheap back-compat signal behind tpu_serving_prefix_hits."""
        return any(len(r) <= len(tokens) and tokens[:len(r)] == r
                   for r in self._registered)

    def _update_page_gauges(self):
        if self._kv_store is None:
            self.metrics.set_gauge("tpu_serving_kv_pages_total", 0)
            self.metrics.set_gauge("tpu_serving_kv_pages_free", 0)
            self.metrics.set_gauge("tpu_serving_kv_pages_shared", 0)
            self._page_stats = None
            return
        with self._prefix_lock:
            stats = self._kv_store.stats()
        # cached for per-step records: shared_count walks the refcount
        # list, too heavy per decode step — step records read this
        # snapshot (refreshed on every arena mutation) instead
        self._page_stats = stats
        self.metrics.set_gauge("tpu_serving_kv_pages_total",
                               stats["pages_total"])
        self.metrics.set_gauge("tpu_serving_kv_pages_free",
                               stats["pages_free"])
        self.metrics.set_gauge("tpu_serving_kv_pages_shared",
                               stats["pages_shared"])

    def _prefill_tokens(self, tokens: list[int], adapter_id: int = 0,
                        single_only: bool = False
                        ) -> tuple[Any, Any, int]:
        """Full prompt -> (last_logits, single-request cache OR _PagedRun,
        tokens served from the prefix cache). The head goes through the
        prefill jit (bucketed to a few fixed lengths so it compiles once
        per bucket, not per prompt length); a prompt longer than
        max_prefill_len continues CHUNKED through the verify kernel.

        Paged engines (the default): with paged-native prefill on the
        chunks scatter STRAIGHT into pre-allocated arena pages
        (_prefill_paged_native — no dense scratch cache exists) and a
        _PagedRun rides the ready queue instead of a cache; otherwise the
        prompt's full pages are matched against the radix trie — matched
        KV GATHERS from the shared arena (no recompute; at least the
        final token always recomputes for its logits) and the suffix
        appends through the verify kernel; then the prompt's own full
        pages are inserted back so the NEXT request sharing this prefix
        skips it, registered or not. Ring/mixed layouts (and
        prefix_cache_enabled=False) fall back to the dense
        registered-prefix store with per-adapter variants.

        ``single_only`` forces the dense-scratch route (fanout groups:
        every member needs its own slot binding, but one _PagedRun's
        partial tail page can belong to exactly one slot)."""
        adapters = self._adapters  # one snapshot per request: a concurrent
        # re-registration must not mix weights between head and chunks
        if self._kv_store is not None:
            return self._prefill_paged(tokens, adapter_id, adapters,
                                       single_only=single_only)
        return self._prefill_dense(tokens, adapter_id, adapters)

    def _prefill_paged_native(self, tokens: list[int], adapter_id: int,
                              adapters, on_chunk=None
                              ) -> Optional[tuple[Any, _PagedRun, int]]:
        """Prefill straight into the arena (ISSUE 14): allocate the
        prompt's whole page run up front, then scatter each chunk's K/V
        rows into those pages through paged_prefill_chunk_step — the
        dense scratch cache and the fill_pages copy never exist on this
        path. A prefix hit's matched pages join the run IN PLACE (no
        gather, no recompute — the chunk step attends them through the
        page table), and the finished run's full pages enter the trie by
        REFERENCE (insert_ready — zero-copy admission). Returns None when
        the pool can't hold the run (caller falls back to the
        dense-scratch route, which degrades per chunk instead).

        ``on_chunk(pages, done)`` fires after every chunk with the run's
        page list and the cumulative committed token count — the
        streamed-handoff hook (the pages the chunk JUST wrote are what
        the stream exports)."""
        from .kv_manager import PoolExhausted
        store = self._kv_store
        t = self.sc.kv_page_tokens
        n = len(tokens)
        n_pages = -(-n // t)
        with self._prefix_lock:
            m = store.match(adapter_id, tokens)
            try:
                tail = (store.alloc_run(n_pages - len(m.pages))
                        if n_pages > len(m.pages) else [])
            except PoolExhausted:
                store.release(m.pages)
                return None
        covered = m.matched_tokens
        pages = list(m.pages) + tail
        if covered:
            self.metrics.incr("tpu_serving_prefix_cache_hits")
            if self._covers_registered(tokens):
                self.metrics.incr("tpu_serving_prefix_hits")
        else:
            self.metrics.incr("tpu_serving_prefix_cache_misses")
            if adapter_id != 0 and self._covers_registered(tokens):
                self.metrics.incr("tpu_serving_prefix_adapter_fills")
        # fixed-width table row (the slot-table shape): the chunk step
        # compiles once per chunk bucket, not per run length; entries past
        # the run stay 0 — a VALID page index the kernels may read but
        # the causal mask never lets contribute
        row = np.zeros((1, self._slot_pages_max), np.int32)
        row[0, :len(pages)] = pages
        table = jnp.asarray(row)
        ad_ids = self._single_ad_ids(adapter_id)
        step = self._chunk_tokens or self.sc.max_prefill_len
        lengths = jnp.asarray([covered], jnp.int32)
        rest = tokens[covered:]
        last_logits = None
        done = covered
        try:
            for start in range(0, len(rest), step):
                chunk = rest[start:start + step]
                ctoks, true_len = self._padded(chunk)
                # the dispatch donates the arena, so it rides _prefix_lock
                # like every arena-touching dispatch (lock covers dispatch
                # only — the wait happens outside)
                with self._prefix_lock:
                    last_logits, arena, lengths = self._paged_chunk(
                        self.params, ctoks, store.arena, table, lengths,
                        true_len, adapters, ad_ids)
                    store.arena = arena
                done += len(chunk)
                self.metrics.incr("tpu_serving_paged_prefill_tokens",
                                  len(chunk))
                if on_chunk is not None:
                    on_chunk(pages, done)
                if self._chunk_tokens:
                    self.metrics.incr("tpu_serving_prefill_chunks")
                    if start + step < len(rest):
                        ran = self._arbiter.yield_for_decode(
                            lambda: self.active_slots > 0)
                        if ran:
                            self.metrics.incr(
                                "tpu_serving_chunk_interleaved_steps", ran)
                            if self.recorder is not None:
                                self.recorder.event("chunk_interleave",
                                                    steps=ran)
            # cache admission BY REFERENCE: the run's full pages join the
            # trie with no copy (the partial tail page stays private).
            # Best-effort like the dense insert.
            try:
                with self._prefix_lock:
                    store.insert_ready(adapter_id, tokens, pages)
                self._publish_prefix(adapter_id, tokens)
            except Exception:  # noqa: BLE001 — caching is best-effort
                log.exception("prefix-cache insert_ready failed; "
                              "serving uncached")
        except Exception:
            with self._prefix_lock:
                store.release(pages)
            raise
        self._update_page_gauges()
        return last_logits, _PagedRun(pages, n, store), covered

    def _prefill_paged(self, tokens: list[int], adapter_id: int,
                       adapters, single_only: bool = False
                       ) -> tuple[Any, Any, int]:
        if self._paged_prefill_on and not single_only:
            out = self._prefill_paged_native(tokens, adapter_id, adapters)
            if out is not None:
                return out
            # pool too full for an up-front run: the dense-scratch route
            # below still works page-by-page (and may evict as it goes)
        store = self._kv_store
        single = None
        with self._prefix_lock:
            m = store.match(adapter_id, tokens)
            if m.pages:
                try:
                    single = store.gather(m.pages, self._fresh_cache(1))
                finally:
                    store.release(m.pages)
        if single is not None:
            self.metrics.incr("tpu_serving_prefix_cache_hits")
            if self._covers_registered(tokens):
                # back-compat series: the registered (pinned) prefix's
                # prefill was skipped, same meaning as the old registry
                self.metrics.incr("tpu_serving_prefix_hits")
            last_logits, single = self._append_chunks(
                single, tokens[m.matched_tokens:], None, adapter_id, adapters)
        else:
            self.metrics.incr("tpu_serving_prefix_cache_misses")
            if adapter_id != 0 and self._covers_registered(tokens):
                # first request from this adapter over a registered prefix
                # computes the adapter-variant KV the trie will now cache —
                # the paged equivalent of the old lazy variant fill
                self.metrics.incr("tpu_serving_prefix_adapter_fills")
            last_logits, single = self._prefill_raw(tokens, adapter_id,
                                                    adapters)
        # cache admission: insert this prompt's full pages (refcount-shared
        # with whatever prefix of them is already cached). Best-effort —
        # a failure here must cost this request nothing but the cache.
        try:
            with self._prefix_lock:
                _, evicted = store.insert(adapter_id, tokens, single)
            if evicted:
                self.metrics.incr("tpu_serving_prefix_cache_evictions",
                                  evicted)
            self._publish_prefix(adapter_id, tokens)
        except Exception:  # noqa: BLE001 — caching is best-effort
            log.exception("prefix-cache insert failed; serving uncached")
        self._update_page_gauges()
        return last_logits, single, m.matched_tokens

    def _prefill_dense(self, tokens: list[int], adapter_id: int,
                       adapters) -> tuple[Any, Params, int]:
        """Registered-prefix path for layouts the paged pool cannot serve
        (ring/mixed) and for prefix_cache_enabled=False: longest registered
        prefix wins, per-adapter variants fill lazily (one prefix prefill
        on an adapter's first request) and are LRU-bounded."""
        dense = self._dense_prefixes
        with self._prefix_lock:
            entry = dense.lookup(tokens)
            var = entry.variants.get(adapter_id) if entry is not None else None
            if var is not None and adapter_id != 0:
                dense.touch(entry, adapter_id)
        if entry is None:
            last_logits, single = self._prefill_raw(tokens, adapter_id,
                                                    adapters)
            return last_logits, single, 0
        if var is None:
            # first request from this adapter: build its prefix variant
            var = self._prefill_raw(entry.tokens, adapter_id, adapters)
            with self._prefix_lock:
                dense.put_variant(entry, adapter_id, var)
            self.metrics.incr("tpu_serving_prefix_adapter_fills")
        else:
            self.metrics.incr("tpu_serving_prefix_hits")
        last_logits, single = var
        last_logits, single = self._append_chunks(
            single, tokens[len(entry.tokens):], last_logits, adapter_id,
            adapters)
        return last_logits, single, len(entry.tokens)

    def register_adapter(self, name: str, source) -> None:
        """Install a LoRA adapter into a free slot of the preallocated
        stacks (no decode-jit recompile — the adapter axis is fixed).
        ``source``: a LoRA-wrapped params tree (models.lora.apply_lora /
        a trained checkpoint) or {target: {"a": (L, in, r), "b": (L, r,
        out), "scale": (L,) or scalar}}. Targets absent from the source
        stay zero (base behavior for that projection); targets not in
        ServingConfig.lora_targets are rejected."""
        if self._adapters is None:
            raise ValueError("engine built without lora_rank; set "
                             "ServingConfig.lora_rank to enable adapters")
        if not name:
            raise ValueError("adapter name required")
        from ...models.lora import is_lora
        if isinstance(source, dict) and "layers" in source:
            src = {t: {"a": w["lora_a"], "b": w["lora_b"],
                       "scale": w["scale"]}
                   for t, w in source["layers"].items() if is_lora(w)}
        else:
            src = source
        if not src:
            raise ValueError("source carries no LoRA adapters")
        extra = set(src) - set(self.sc.lora_targets)
        if extra:
            raise ValueError(f"adapter targets {sorted(extra)} not in "
                             f"lora_targets {self.sc.lora_targets}")
        with self._adapter_lock:
            slot = self._adapter_names.get(name)
            if slot is None:
                slot = len(self._adapter_names) + 1
                if slot > self.sc.max_adapters:
                    raise ValueError(
                        f"adapter registry full ({self.sc.max_adapters})")
            new_tree = {}
            for t, ad in self._adapters.items():
                if t not in src:
                    new_tree[t] = ad
                    continue
                a = jnp.asarray(src[t]["a"], ad["a"].dtype)
                bm = jnp.asarray(src[t]["b"], ad["b"].dtype)
                want_a = ad["a"].shape[0], ad["a"].shape[2], ad["a"].shape[3]
                if a.shape != want_a or bm.shape != (
                        ad["b"].shape[0], ad["b"].shape[2], ad["b"].shape[3]):
                    raise ValueError(
                        f"{t}: adapter shapes {a.shape}/{bm.shape} don't "
                        f"match rank-{self.sc.lora_rank} stacks for this "
                        "model")
                scale = jnp.broadcast_to(
                    jnp.asarray(src[t]["scale"], jnp.float32),
                    (ad["scale"].shape[0],))
                new_tree[t] = {"a": ad["a"].at[:, slot].set(a),
                               "b": ad["b"].at[:, slot].set(bm),
                               "scale": ad["scale"].at[:, slot].set(scale)}
            self._adapters = new_tree
            self._adapter_names[name] = slot
        # a RE-registered adapter slot carries new weights: its cached
        # prefix KV (trie subtree / dense variants) was computed with the
        # old ones — drop it
        with self._prefix_lock:
            if self._kv_store is not None:
                self._kv_store.trie.drop_adapter(slot)
            if self._dense_prefixes is not None:
                self._dense_prefixes.drop_adapter(slot)
        self._update_page_gauges()

    def register_prefix(self, tokens: list[int]) -> None:
        """Cache the KV of a shared prompt prefix (system prompt) ONCE and
        PIN it: its trie pages are never evicted, so any later prompt that
        starts with it skips its full pages' prefill entirely (gathered
        from the arena — verify-kernel writes produce fresh buffers, never
        mutating shared pages). Longest match wins naturally in the trie.

        Registrations are DEDUPED (re-registering the same tokens is a
        no-op) and capped at ``max_prefixes`` — a restart/retry loop
        against /prefix must not pin pages per POST until the pod OOMs.
        Note page granularity: the prefix's tail past its last full page
        (and prefixes shorter than one page) still recompute per request.
        Ring/mixed engines pin a dense single-slot cache copy instead
        (their positions ring-overwrite, so pages cannot represent them)."""
        if not tokens:
            raise ValueError("empty prefix")
        if len(tokens) > self.sc.cache_len - 1:
            raise ValueError(f"prefix length {len(tokens)} > cache budget "
                             f"{self.sc.cache_len - 1}")
        tokens = list(tokens)
        with self._prefix_lock:
            if tokens in self._registered:
                return  # idempotent
            if len(self._registered) >= self.sc.max_prefixes:
                raise ValueError(
                    f"prefix registry full ({self.sc.max_prefixes}); each "
                    "entry pins KV in HBM — raise max_prefixes or restart "
                    "to clear")
        logits, single, _ = self._prefill_tokens(tokens)
        with self._prefix_lock:
            if tokens in self._registered:
                if isinstance(single, _PagedRun):
                    single.store.release(single.pages)
                return  # raced with an identical registration
            if len(self._registered) >= self.sc.max_prefixes:
                # re-check: a concurrent registration may have filled the
                # registry while we prefilled outside the lock
                if isinstance(single, _PagedRun):
                    single.store.release(single.pages)
                raise ValueError(
                    f"prefix registry full ({self.sc.max_prefixes}); each "
                    "entry pins KV in HBM — raise max_prefixes or restart "
                    "to clear")
            self._registered.append(tokens)
            if isinstance(single, _PagedRun):
                # paged-native prefill: the prefix's pages already sit in
                # the arena (insert_ready adopted them unpinned) — this
                # second walk PINS them, then the run's own references
                # drop (the trie's pinned refs keep the KV)
                evicted = 0
                if single.store is self._kv_store:
                    self._kv_store.insert_ready(0, tokens, single.pages,
                                                pin=True)
                single.store.release(single.pages)
            elif self._kv_store is not None:
                _, evicted = self._kv_store.insert(0, tokens, single,
                                                   pin=True)
            else:
                evicted = 0
                if not self._dense_prefixes.has(tokens):
                    self._dense_prefixes.add(tokens, (logits, single))
        if evicted:
            self.metrics.incr("tpu_serving_prefix_cache_evictions", evicted)
        if self._kv_store is not None:
            # registered prefixes are the directory's best customers:
            # pinned pages can never go GONE under a pull
            self._publish_prefix(0, tokens)
        self._update_page_gauges()

    # -- fleet prefix directory (ISSUE 16) -------------------------------------

    def _adapter_name_for(self, adapter_id: int) -> Optional[str]:
        """The registered name behind an adapter slot ("" = base slot 0);
        None when the slot has no live name (adapter unregistered while
        its request was in flight) — the caller skips the publish rather
        than key the run under the wrong adapter."""
        if adapter_id == 0:
            return ""
        with self._adapter_lock:
            for name, slot in self._adapter_names.items():
                if slot == adapter_id:
                    return name
        return None

    def _adapter_root_id(self, adapter: str) -> int:
        """Adapter NAME -> trie root slot, the inverse of
        ``_adapter_name_for`` ("" = base root 0). The pull/adopt doors
        resolve directory-carried adapter names through this; an unknown
        name raises KVPullMiss — the directory claimed an adapter this
        replica does not hold, same fall-back-to-prefill as evicted
        pages."""
        from ...fleet.handoff import KVPullMiss
        if not adapter:
            return 0
        with self._adapter_lock:
            slot = self._adapter_names.get(adapter)
        if slot is None:
            raise KVPullMiss(f"adapter {adapter!r} is not registered on "
                             "this replica")
        return slot

    def _publish_prefix(self, adapter_id: int, tokens: list) -> None:
        """Queue this run's LONGEST page-boundary key for the global
        prefix directory (ReplicaReporter drains the queue into
        heartbeats). One key per run suffices: the router walks a
        request's chain longest-first, and incremental chunk hashing
        makes every extension's chain contain this key. Best-effort by
        design — a lost publish costs the fleet one pull opportunity,
        never a request."""
        try:
            t = self.sc.kv_page_tokens
            n_pages = len(tokens) // t
            if n_pages < 1:
                return
            adapter = self._adapter_name_for(adapter_id)
            if adapter is None:
                return
            from ...fleet.prefix_directory import prefix_key
            key = prefix_key(tokens[:n_pages * t], t, adapter)
            with self._publish_lock:
                self._prefix_publishes[key] = {
                    "key": key, "pages": n_pages,
                    "model": self.cfg.name, "adapter": adapter}
                self._prefix_publishes.move_to_end(key)
                while len(self._prefix_publishes) > 256:
                    self._prefix_publishes.popitem(last=False)
            hook = self.prefix_publish_hook
            if hook is not None:
                hook()
        except Exception:  # noqa: BLE001 — publishing is best-effort
            log.exception("prefix publish failed; the directory misses "
                          "this run until its next insert")

    def take_prefix_publishes(self) -> list:
        """Drain pending directory publishes for a heartbeat. The caller
        (ReplicaReporter) re-queues what it drained if the beat fails —
        publishes are pending-until-acked, not fire-and-forget."""
        with self._publish_lock:
            out = list(self._prefix_publishes.values())
            self._prefix_publishes.clear()
        return out

    def requeue_prefix_publishes(self, publishes: list) -> None:
        """Give back publishes from a FAILED heartbeat. Newer pending
        entries win a key collision (they carry fresher page counts)."""
        with self._publish_lock:
            for pub in publishes:
                key = pub.get("key")
                if key and key not in self._prefix_publishes:
                    self._prefix_publishes[key] = pub
                    self._prefix_publishes.move_to_end(key, last=False)
            while len(self._prefix_publishes) > 256:
                self._prefix_publishes.popitem(last=False)

    # -- disaggregated KV handoff (ISSUE 9) ------------------------------------

    def export_handoff(self, tokens: list[int]) -> dict:
        """Prefill-role half of a handoff: run ``tokens`` through the
        normal prefix-cache prefill path (matched pages skip compute; the
        prompt's full pages land in this arena) and serialize the run for
        a decode replica to adopt. Returns {"blob", "pages",
        "covered_tokens", "matched_tokens"} — matched_tokens is how much
        THIS replica's cache already held before the prefill.

        Runs on the caller's (HTTP handler) thread like ``embed()``:
        device work serializes with the engine loop's dispatches, which a
        prefill-role replica — the intended caller — barely has. The hop
        is this replica's LOAD: it never touches the scheduler queue or a
        slot, so ``handoff_inflight`` (surfaced via debug_snapshot ->
        ReplicaReporter queue_depth) and a TTFT observation make the
        prefill pool's autoscaler signals see the work — without them a
        saturated prefill pool reports itself idle and scales to min."""
        from ...fleet.handoff import HandoffError, serialize_pages
        if self._kv_store is None:
            raise HandoffError("this replica has no paged KV arena "
                               "(ring/mixed layout or prefix cache "
                               "disabled) — it cannot hand off KV")
        tokens = list(tokens)
        if not tokens:
            raise ValueError("empty prompt")
        if len(tokens) > self.sc.cache_len - 1:
            raise ValueError(f"prompt length {len(tokens)} > cache budget "
                             f"{self.sc.cache_len - 1}")
        started = self._perf()
        with self._handoff_lock:
            self.handoff_inflight += 1
        try:
            _, _single, matched = self._prefill_tokens(tokens)
            if isinstance(_single, _PagedRun):
                # native paged prefill returns the run's own references;
                # the trie already holds its copies (insert_ready), so the
                # match_full below still finds the pages after we let go
                with self._prefix_lock:
                    _single.store.release(_single.pages)
            # ONE store reference for match -> export -> release: crash
            # recovery may rebind self._kv_store between these steps, and
            # releasing old-store page ids against the rebuilt pool would
            # corrupt refcounts (releasing on the discarded store is
            # harmless — it is dropped wholesale)
            with self._prefix_lock:
                store = self._kv_store
                m = store.match_full(0, tokens)
                frags = store.export_pages(m.pages) if m.pages else {}
            try:
                if not m.pages:
                    raise HandoffError(
                        f"no full pages to hand off for a {len(tokens)}-"
                        f"token prompt at page size "
                        f"{self.sc.kv_page_tokens} (prompt shorter than "
                        "one page, or the pool evicted it)")
                # host copies OUTSIDE the lock: the fragments are private
                # device buffers, valid across later arena donations
                sections = {name: np.asarray(a) for name, a in frags.items()}
                blob = serialize_pages(tokens[:m.matched_tokens],
                                       self.sc.kv_page_tokens, sections,
                                       model=self.cfg.name)
            finally:
                with self._prefix_lock:
                    store.release(m.pages)
        except Exception:
            self.metrics.incr("tpu_serving_kv_handoff_failures")
            raise
        finally:
            with self._handoff_lock:
                self.handoff_inflight -= 1
        with self._handoff_lock:
            self.handoffs_total += 1
        self.metrics.incr("tpu_serving_kv_handoff_pages", len(m.pages))
        self.metrics.incr("tpu_serving_kv_handoff_bytes", len(blob))
        # the hop IS a prefill replica's time-to-first-token contribution:
        # feed the TTFT histogram so the pool's TTFT-burn signal has data
        self.metrics.observe("tpu_serving_ttft_seconds",
                             self._perf() - started)
        return {"blob": blob, "pages": len(m.pages),
                "covered_tokens": m.matched_tokens,
                "matched_tokens": matched}

    def adopt_handoff(self, blob: bytes, adapter: str = "") -> dict:
        """Decode-role half: validate and adopt a serialized page run
        into this arena through the trie — the engine's next prompt match
        then references the adopted pages zero-copy and only the sub-page
        tail recomputes. The handoff counters move ONLY after the
        adoption actually landed (a failed adoption is a failure, never
        an optimistic hit). ``adapter`` names the trie root the run
        belongs under ("" = base) — directory pulls adopt adapter-variant
        runs through the same door. Returns {pages, tokens, bytes,
        evicted}."""
        from ...fleet.handoff import HandoffError, deserialize_pages
        try:
            if self._kv_store is None:
                raise HandoffError("this replica has no paged KV arena "
                                   "(ring/mixed layout or prefix cache "
                                   "disabled) — it cannot adopt KV")
            root = self._adapter_root_id(adapter)
            with self._prefix_lock:
                spec = self._kv_store.section_spec()
            header, sections = deserialize_pages(
                blob, expect_page_tokens=self.sc.kv_page_tokens,
                expect_sections=spec, expect_model=self.cfg.name)
            if len(header["tokens"]) > self.sc.cache_len:
                raise HandoffError(
                    f"handoff spans {len(header['tokens'])} tokens, over "
                    f"this replica's cache budget {self.sc.cache_len}")
            with self._prefix_lock:
                added, evicted = self._kv_store.adopt(
                    root, header["tokens"], sections)
        except Exception:
            self.metrics.incr("tpu_serving_kv_handoff_failures")
            raise
        self.metrics.incr("tpu_serving_kv_handoff_pages", header["n_pages"])
        self.metrics.incr("tpu_serving_kv_handoff_bytes", len(blob))
        if evicted:
            self.metrics.incr("tpu_serving_prefix_cache_evictions", evicted)
        self._update_page_gauges()
        # adopted pages are as pullable as locally-prefilled ones: tell
        # the directory this replica is now a holder too
        self._publish_prefix(root, header["tokens"])
        return {"pages": header["n_pages"], "added": added,
                "tokens": len(header["tokens"]), "bytes": len(blob),
                "evicted": evicted}

    # -- device-native handoff (ISSUE 11) --------------------------------------

    def export_handoff_device(self, tokens: list[int]) -> dict:
        """``export_handoff`` minus the host round-trip: run the prompt
        through the prefix-cache prefill path and hand back the run's
        FRESH DEVICE buffers (export_pages — valid across later arena
        donations) plus the tokens they cover. Nothing is serialized and
        nothing touches numpy: a co-located decode engine adopts the
        arrays directly (fleet/device_transfer.device_push). Same load
        accounting as the wire export (handoff_inflight, TTFT
        observation, handoffs_total)."""
        from ...fleet.handoff import HandoffError
        if self._kv_store is None:
            raise HandoffError("this replica has no paged KV arena "
                               "(ring/mixed layout or prefix cache "
                               "disabled) — it cannot hand off KV")
        tokens = list(tokens)
        if not tokens:
            raise ValueError("empty prompt")
        if len(tokens) > self.sc.cache_len - 1:
            raise ValueError(f"prompt length {len(tokens)} > cache budget "
                             f"{self.sc.cache_len - 1}")
        started = self._perf()
        with self._handoff_lock:
            self.handoff_inflight += 1
        try:
            _, _single, matched = self._prefill_tokens(tokens)
            if isinstance(_single, _PagedRun):
                # drop the run's own references; the trie's insert_ready
                # copies keep the pages alive for the match_full below
                with self._prefix_lock:
                    _single.store.release(_single.pages)
            # ONE store reference across match -> export -> release, like
            # export_handoff (crash recovery may rebind _kv_store)
            with self._prefix_lock:
                store = self._kv_store
                m = store.match_full(0, tokens)
                frags = store.export_pages(m.pages) if m.pages else {}
            try:
                if not m.pages:
                    raise HandoffError(
                        f"no full pages to hand off for a {len(tokens)}-"
                        f"token prompt at page size "
                        f"{self.sc.kv_page_tokens} (prompt shorter than "
                        "one page, or the pool evicted it)")
                nbytes = sum(int(a.size) * int(a.dtype.itemsize)
                             for a in frags.values())
            finally:
                with self._prefix_lock:
                    store.release(m.pages)
        except Exception:
            self.metrics.incr("tpu_serving_kv_handoff_failures")
            raise
        finally:
            with self._handoff_lock:
                self.handoff_inflight -= 1
        with self._handoff_lock:
            self.handoffs_total += 1
        self.metrics.incr("tpu_serving_kv_handoff_pages", len(m.pages))
        self.metrics.incr("tpu_serving_kv_handoff_device_runs")
        self.metrics.incr("tpu_serving_kv_handoff_device_bytes", nbytes)
        self.metrics.observe("tpu_serving_ttft_seconds",
                             self._perf() - started)
        return {"tokens": tokens[:m.matched_tokens], "sections": frags,
                "pages": len(m.pages), "bytes": nbytes,
                "covered_tokens": m.matched_tokens,
                "matched_tokens": matched}

    def adopt_handoff_device(self, tokens: list, sections: dict, *,
                             model: str = "", adapter: str = "") -> dict:
        """Decode half of a device-path handoff: validate the run's
        geometry against this arena (fleet/handoff.check_device_sections
        — the ONE device-contract definition the stream assembler shares,
        here with pow2-padded export_run widths accepted and trimmed by a
        device-side slice) and adopt the DEVICE arrays through the trie —
        the scatter into the arena is the only data movement; no
        deserialization, no host staging. Counters move only after the
        adoption lands (all-or-nothing, like the wire path)."""
        from ...fleet.handoff import HandoffError, check_device_sections
        try:
            if self._kv_store is None:
                raise HandoffError("this replica has no paged KV arena "
                                   "(ring/mixed layout or prefix cache "
                                   "disabled) — it cannot adopt KV")
            tokens = list(tokens)
            if len(tokens) > self.sc.cache_len:
                raise HandoffError(
                    f"device run spans {len(tokens)} tokens, over this "
                    f"replica's cache budget {self.sc.cache_len}")
            root = self._adapter_root_id(adapter)
            with self._prefix_lock:
                spec = self._kv_store.section_spec()
            n, trimmed, nbytes = check_device_sections(
                tokens, sections,
                expect_page_tokens=self.sc.kv_page_tokens,
                expect_sections=spec, expect_model=self.cfg.name,
                model=model, allow_padded=True)
            with self._prefix_lock:
                added, evicted = self._kv_store.adopt(
                    root, [int(tk) for tk in tokens], trimmed)
        except Exception:
            self.metrics.incr("tpu_serving_kv_handoff_failures")
            raise
        self.metrics.incr("tpu_serving_kv_handoff_pages", n)
        self.metrics.incr("tpu_serving_kv_handoff_device_runs")
        self.metrics.incr("tpu_serving_kv_handoff_device_bytes", nbytes)
        if evicted:
            self.metrics.incr("tpu_serving_prefix_cache_evictions", evicted)
        self._update_page_gauges()
        self._publish_prefix(root, [int(tk) for tk in tokens])
        return {"pages": n, "added": added, "tokens": len(tokens),
                "bytes": nbytes, "evicted": evicted}

    def adopt_handoff_chunk_device(self, stream_id: str, seq: int,
                                   tokens: list, sections: dict, *,
                                   final: bool = False,
                                   total_tokens=None,
                                   model: str = "") -> dict:
        """Decode half of a STREAMED device handoff: one device fragment
        through the same HandoffStreamAssembler seq/TTL state machine the
        wire frames use (feed_fragment — strict order, idle-TTL expiry,
        all-or-nothing close), just without serialize/deserialize in the
        middle. Fragments buffer as device arrays; the arena moves only
        when the final fragment closes a fully-valid stream."""
        from ...fleet.handoff import HandoffError
        try:
            if self._kv_store is None:
                raise HandoffError("this replica has no paged KV arena "
                                   "(ring/mixed layout or prefix cache "
                                   "disabled) — it cannot adopt KV")
            with self._handoff_lock:
                assembler = self._assembler()
                try:
                    done = assembler.feed_fragment(
                        stream_id, seq, tokens, sections, final=final,
                        total_tokens=total_tokens, model=model)
                except HandoffError:
                    self.metrics.incr(
                        "tpu_serving_kv_handoff_stream_rejects")
                    raise
            self.metrics.incr("tpu_serving_kv_handoff_stream_frames")
            if not done["final"]:
                return {"ok": True, "final": False, "seq": done["seq"]}
            if len(done["tokens"]) > self.sc.cache_len:
                raise HandoffError(
                    f"stream spans {len(done['tokens'])} tokens, over "
                    f"this replica's cache budget {self.sc.cache_len}")
            merged = self._merged_stream_sections(done)
            nbytes = sum(int(a.size) * int(a.dtype.itemsize)
                         for a in merged.values())
            with self._prefix_lock:
                added, evicted = self._kv_store.adopt(
                    0, done["tokens"], merged)
        except Exception:
            self.metrics.incr("tpu_serving_kv_handoff_failures")
            raise
        n_pages = len(done["tokens"]) // self.sc.kv_page_tokens
        self.metrics.incr("tpu_serving_kv_handoff_pages", n_pages)
        self.metrics.incr("tpu_serving_kv_handoff_device_runs")
        self.metrics.incr("tpu_serving_kv_handoff_device_bytes", nbytes)
        if evicted:
            self.metrics.incr("tpu_serving_prefix_cache_evictions", evicted)
        self._update_page_gauges()
        self._publish_prefix(0, done["tokens"])
        return {"ok": True, "final": True, "seq": done["seq"],
                "pages": n_pages, "added": added,
                "tokens": len(done["tokens"]), "bytes": nbytes,
                "frames": done["frames"], "evicted": evicted}

    @staticmethod
    def _merged_stream_sections(done: dict) -> dict:
        """One {name: (L, n, T, ...)} dict from a closed stream's
        per-frame section dicts, concatenated DEVICE-side (jnp accepts
        numpy frames too, so a stream whose frames arrived through BOTH
        doors — wire frames and device fragments share one seq lane —
        still merges instead of KeyError-ing on a missing wire-only
        field)."""
        frames = done["section_frames"]
        if len(frames) == 1:
            return frames[0]
        return {name: jnp.concatenate([f[name] for f in frames], axis=1)
                for name in frames[0]}

    # -- KV-fabric pull doors (ISSUE 16) ---------------------------------------

    def export_pull(self, tokens: list[int], adapter: str = "") -> dict:
        """Owner half of a directory pull: serialize the pages this trie
        ALREADY holds for ``tokens`` — match-only, never prefilling. The
        whole point of a pull is skipping compute; an owner that lost
        the pages since its publish raises KVPullMiss (the /kv_pull door
        answers 404 gone, the router invalidates the directory entry and
        the cold replica prefills for itself — one miss, no retry). Same
        ONE-store-reference discipline and load accounting as
        export_handoff. Returns {"blob", "pages", "covered_tokens"}."""
        from ...fleet.handoff import KVPullMiss, serialize_pages
        if self._kv_store is None:
            raise KVPullMiss("this replica has no paged KV arena — "
                             "nothing to pull")
        tokens = list(tokens)
        if not tokens:
            raise ValueError("empty prompt")
        root = self._adapter_root_id(adapter)
        with self._handoff_lock:
            self.handoff_inflight += 1
        try:
            with self._prefix_lock:
                store = self._kv_store
                m = store.match_full(root, tokens)
                frags = store.export_pages(m.pages) if m.pages else {}
            try:
                if not m.pages:
                    raise KVPullMiss(
                        f"no cached full pages for a {len(tokens)}-token "
                        f"prompt at page size {self.sc.kv_page_tokens} "
                        "(evicted since the directory publish)")
                # host copies OUTSIDE the lock, like export_handoff
                sections = {name: np.asarray(a)
                            for name, a in frags.items()}
                blob = serialize_pages(tokens[:m.matched_tokens],
                                       self.sc.kv_page_tokens, sections,
                                       model=self.cfg.name)
            finally:
                with self._prefix_lock:
                    store.release(m.pages)
        except KVPullMiss:
            raise  # clean GONE — directory staleness, not a failure
        except Exception:
            self.metrics.incr("tpu_serving_kv_pull_failures")
            raise
        finally:
            with self._handoff_lock:
                self.handoff_inflight -= 1
        self.metrics.incr("tpu_serving_kv_pull_runs")
        self.metrics.incr("tpu_serving_kv_pull_bytes", len(blob))
        return {"blob": blob, "pages": len(m.pages),
                "covered_tokens": m.matched_tokens}

    def export_pull_device(self, tokens: list[int],
                           adapter: str = "") -> dict:
        """``export_pull`` minus serialization: fresh device buffers for
        the matched run, adopted in-process by device_pull on the cold
        engine. Carries the owner's model name so the puller's own adopt
        door enforces cross-model rejection even device-native."""
        from ...fleet.handoff import KVPullMiss
        if self._kv_store is None:
            raise KVPullMiss("this replica has no paged KV arena — "
                             "nothing to pull")
        tokens = list(tokens)
        if not tokens:
            raise ValueError("empty prompt")
        root = self._adapter_root_id(adapter)
        with self._handoff_lock:
            self.handoff_inflight += 1
        try:
            with self._prefix_lock:
                store = self._kv_store
                m = store.match_full(root, tokens)
                frags = store.export_pages(m.pages) if m.pages else {}
            try:
                if not m.pages:
                    raise KVPullMiss(
                        f"no cached full pages for a {len(tokens)}-token "
                        f"prompt at page size {self.sc.kv_page_tokens} "
                        "(evicted since the directory publish)")
                nbytes = sum(int(a.size) * int(a.dtype.itemsize)
                             for a in frags.values())
            finally:
                with self._prefix_lock:
                    store.release(m.pages)
        except KVPullMiss:
            raise  # clean GONE — directory staleness, not a failure
        except Exception:
            self.metrics.incr("tpu_serving_kv_pull_failures")
            raise
        finally:
            with self._handoff_lock:
                self.handoff_inflight -= 1
        self.metrics.incr("tpu_serving_kv_pull_runs")
        self.metrics.incr("tpu_serving_kv_pull_bytes", nbytes)
        return {"tokens": tokens[:m.matched_tokens], "sections": frags,
                "pages": len(m.pages), "bytes": nbytes,
                "covered_tokens": m.matched_tokens,
                "model": self.cfg.name}

    def _assembler(self):
        """The decode side's stream assembler, built lazily (needs the
        arena's section spec). Caller holds _handoff_lock."""
        from ...fleet.handoff import HandoffStreamAssembler
        if self._stream_assembler is None:
            with self._prefix_lock:
                spec = self._kv_store.section_spec()
            self._stream_assembler = HandoffStreamAssembler(
                expect_page_tokens=self.sc.kv_page_tokens,
                expect_sections=spec, expect_model=self.cfg.name,
                clock=self._perf)
        return self._stream_assembler

    # -- streaming chunked handoff (ISSUE 10) ----------------------------------

    def export_handoff_stream(self, tokens: list[int], emit) -> dict:
        """Streaming half of a handoff: run ``tokens`` through the
        CHUNKED prefill path, inserting each completed chunk's full pages
        into this arena as a page run and handing them to ``emit`` while
        the next chunk is still computing — the caller's sender thread
        serializes and pushes frames, so two-hop TTFT approaches
        max(compute, transfer) instead of their sum.

        ``emit(fragment)`` fires in strict order with {"seq", "final",
        "tokens", "sections"} — sections are FRESH DEVICE copies padded
        to a pow2 page bucket (PagedKVStore.export_run), valid across
        later arena donations: the consumer thread does the host copy and
        trims to ``len(tokens) // kv_page_tokens`` pages, so compute
        never stalls on the sync; the closing fragment carries empty
        sections and ``total_tokens``. A raising emit aborts the export
        (the hop fails loudly; the router falls back). Pages the trie
        already holds stream FIRST — a prefix hit's pages move with zero
        recompute. Eviction racing the stream degrades cleanly: the
        stream closes with the contiguous prefix it could export (a
        partial handoff is valid, exactly like the monolithic path's).

        Needs chunked prefill on (serving_chunk_tokens > 0) — without
        chunks there is nothing to overlap; callers use export_handoff.

        Returns {"pages", "chunks", "covered_tokens", "matched_tokens"}.
        """
        from ...fleet.handoff import HandoffError
        if self._kv_store is None:
            raise HandoffError("this replica has no paged KV arena "
                               "(ring/mixed layout or prefix cache "
                               "disabled) — it cannot hand off KV")
        if not self._chunk_tokens:
            raise HandoffError("streamed handoff needs chunked prefill "
                               "(serving_chunk_tokens > 0); use "
                               "export_handoff")
        tokens = list(tokens)
        if not tokens:
            raise ValueError("empty prompt")
        if len(tokens) > self.sc.cache_len - 1:
            raise ValueError(f"prompt length {len(tokens)} > cache budget "
                             f"{self.sc.cache_len - 1}")
        t = self.sc.kv_page_tokens
        total_pages = len(tokens) // t
        if total_pages == 0:
            raise HandoffError(
                f"no full pages to hand off for a {len(tokens)}-token "
                f"prompt at page size {t}")
        started = self._perf()
        with self._handoff_lock:
            self.handoff_inflight += 1
        state = {"seq": 0, "sent": 0, "stopped": False}

        def flush(done: int):
            """Export pages [sent, done // t) — the contiguous prefix the
            trie still holds. ONE store reference per flush (crash
            recovery may rebind _kv_store; releasing against the captured
            store is always safe — a discarded store drops wholesale)."""
            if state["stopped"]:
                return
            want = min(done // t, total_pages)
            if want <= state["sent"]:
                return
            with self._prefix_lock:
                store = self._kv_store
                m = store.match_full(0, tokens[:done])
                take = min(want, m.matched_tokens // t)
                if take <= state["sent"]:
                    # eviction raced the stream: close with what we sent
                    store.release(m.pages)
                    state["stopped"] = True
                    return
                frags = store.export_run(m.pages[state["sent"]:take])
                # export_run returns FRESH device copies (pow2-padded)
                # valid across later arena donations, and the refs only
                # guard the DISPATCH (its contract) — so release here and
                # ship the device arrays: the consumer thread does the
                # host copy + padding trim, keeping that sync OFF the
                # compute thread. Copying here would serialize transfer
                # back into compute — the very stall the stream exists to
                # hide.
                store.release(m.pages)
            emit({"seq": state["seq"], "final": False,
                  "tokens": tokens[state["sent"] * t:take * t],
                  "sections": frags})
            state["seq"] += 1
            state["sent"] = take
            if take < want:
                state["stopped"] = True

        matched0 = 0
        run = None
        try:
            adapters = self._adapters  # one snapshot, like _prefill_tokens
            if self._paged_prefill_on:
                # paged-NATIVE export (ISSUE 14): chunks scatter straight
                # into arena pages and the stream exports the pages each
                # chunk JUST wrote — no dense scratch cache, no gather,
                # no fill_pages between compute and wire.
                with self._prefix_lock:
                    m0 = self._kv_store.match(0, tokens)
                    self._kv_store.release(m0.pages)
                flush(m0.matched_tokens)  # cached pages move pre-compute

                def on_chunk_native(pages, done):
                    # cache admission BY REFERENCE per chunk
                    # (insert_ready): the chunk's completed full pages
                    # enter the trie with no copy, then stream out.
                    # Best-effort like the dense insert — a failure
                    # closes the stream short, never fails the prefill.
                    try:
                        with self._prefix_lock:
                            self._kv_store.insert_ready(0, tokens[:done],
                                                        pages)
                    except Exception:  # noqa: BLE001 — best-effort
                        log.exception("chunk insert_ready failed; handoff "
                                      "stream closes short")
                    flush(done)

                out = self._prefill_paged_native(
                    tokens, 0, adapters, on_chunk=on_chunk_native)
                if out is not None:
                    _, run, matched0 = out
            if run is None:
                # dense-scratch route: paged_prefill off, or the pool
                # couldn't hold the whole run up front
                with self._prefix_lock:
                    store = self._kv_store
                    m = store.match(0, tokens)
                    single = None
                    if m.pages:
                        try:
                            single = store.gather(m.pages,
                                                  self._fresh_cache(1))
                        finally:
                            store.release(m.pages)
                covered = m.matched_tokens if single is not None else 0
                matched0 = covered
                if single is not None:
                    self.metrics.incr("tpu_serving_prefix_cache_hits")
                else:
                    self.metrics.incr("tpu_serving_prefix_cache_misses")
                flush(covered)  # already-cached pages move before compute

                def on_chunk(sgl, done):
                    # cache admission per chunk: the chunk's completed full
                    # pages land in the arena as a page run, then stream out.
                    # Best-effort like the monolithic insert — a failure
                    # closes the stream short, never fails the prefill.
                    try:
                        with self._prefix_lock:
                            _, evicted = self._kv_store.insert(
                                0, tokens[:done], sgl)
                        if evicted:
                            self.metrics.incr(
                                "tpu_serving_prefix_cache_evictions", evicted)
                    except Exception:  # noqa: BLE001 — caching is best-effort
                        log.exception("chunk insert failed; handoff stream "
                                      "closes short")
                    flush(done)

                if single is None:
                    self._prefill_raw(tokens, 0, adapters, on_chunk=on_chunk)
                else:
                    self._append_chunks(single, tokens[covered:], None, 0,
                                        adapters, on_chunk=on_chunk,
                                        done=covered)
            flush(len(tokens))
            if run is not None:
                # the export holds no decode slot: once the final flush has
                # moved everything, the run's own references drop — the
                # trie's refs (insert_ready) keep the pages cached
                with self._prefix_lock:
                    run.store.release(run.pages)
                run = None
            if state["sent"] == 0:
                raise HandoffError("no pages survived to hand off (the "
                                   "pool evicted the stream as it was "
                                   "computed)")
            data_frames = state["seq"]
            emit({"seq": state["seq"], "final": True, "tokens": [],
                  "sections": {}, "total_tokens": state["sent"] * t})
            state["seq"] += 1
        except Exception:
            if run is not None:
                # a failed export must not strand the run's references
                with self._prefix_lock:
                    run.store.release(run.pages)
            self.metrics.incr("tpu_serving_kv_handoff_failures")
            raise
        finally:
            with self._handoff_lock:
                self.handoff_inflight -= 1
        with self._handoff_lock:
            self.handoffs_total += 1
        self.metrics.incr("tpu_serving_kv_handoff_pages", state["sent"])
        self.metrics.incr("tpu_serving_kv_handoff_stream_frames",
                          state["seq"])
        self._update_page_gauges()
        # the hop IS this prefill replica's TTFT contribution (see
        # export_handoff)
        self.metrics.observe("tpu_serving_ttft_seconds",
                             self._perf() - started)
        # "chunks" counts DATA frames — the number an operator correlates
        # with tpu_serving_prefill_chunks and the timeline's page rows;
        # "frames" includes the empty close frame (what actually moved)
        return {"pages": state["sent"], "chunks": data_frames,
                "frames": state["seq"],
                "covered_tokens": state["sent"] * t,
                "matched_tokens": matched0}

    def adopt_handoff_chunk(self, blob: bytes) -> dict:
        """Decode-role half of a STREAMED handoff: one sequence-numbered
        chunk frame in. Frames buffer HOST-side in strict order
        (fleet/handoff.HandoffStreamAssembler); the arena — and every
        counter — moves ONLY when the final frame lands and the whole
        stream checks out: all-or-nothing page accounting, so a torn,
        duplicate, reordered or stale stream drops whole and the arena
        stays exactly as it was. Returns {"ok": True, "final": False}
        mid-stream, adoption stats on the final frame."""
        from ...fleet.handoff import HandoffError
        try:
            if self._kv_store is None:
                raise HandoffError("this replica has no paged KV arena "
                                   "(ring/mixed layout or prefix cache "
                                   "disabled) — it cannot adopt KV")
            with self._handoff_lock:
                assembler = self._assembler()
                try:
                    done = assembler.feed(blob)
                except HandoffError:
                    self.metrics.incr(
                        "tpu_serving_kv_handoff_stream_rejects")
                    raise
            self.metrics.incr("tpu_serving_kv_handoff_stream_frames")
            if not done["final"]:
                return {"ok": True, "final": False, "seq": done["seq"]}
            if len(done["tokens"]) > self.sc.cache_len:
                raise HandoffError(
                    f"stream spans {len(done['tokens'])} tokens, over "
                    f"this replica's cache budget {self.sc.cache_len}")
            with self._prefix_lock:
                added, evicted = self._kv_store.adopt(
                    # the per-frame merge (not _close's numpy concat):
                    # a stream may legally mix wire frames and device
                    # fragments on one seq lane
                    0, done["tokens"], self._merged_stream_sections(done))
        except Exception:
            self.metrics.incr("tpu_serving_kv_handoff_failures")
            raise
        n_pages = len(done["tokens"]) // self.sc.kv_page_tokens
        self.metrics.incr("tpu_serving_kv_handoff_pages", n_pages)
        self.metrics.incr("tpu_serving_kv_handoff_bytes", done["bytes"])
        if evicted:
            self.metrics.incr("tpu_serving_prefix_cache_evictions", evicted)
        self._update_page_gauges()
        self._publish_prefix(0, done["tokens"])
        return {"ok": True, "final": True, "seq": done["seq"],
                "pages": n_pages, "added": added,
                "tokens": len(done["tokens"]), "bytes": done["bytes"],
                "frames": done["frames"], "evicted": evicted}

    def _prefill_loop(self):
        """Dedicated prefill worker: drains the request queue, runs the
        prefill jit, and hands (request, cache, first token) to the engine.
        The bounded ready queue provides backpressure so at most ``slots``
        prefilled caches are in flight."""
        while not self._stop.is_set():
            # pop + transit-count under one lock (get_nowait, not a blocking
            # get: the lock must never be held while waiting) so `drained`
            # can never observe the request in neither place
            with self._transit_lock:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    req = None
                else:
                    self._transit += 1
            if req is None:
                # wait for a submit's set() (immediate wake), clear, then
                # loop — the pop-first ordering above means a put racing
                # the clear is still found on the next pass. The timeout
                # is only a liveness backstop for the stop flag.
                self._queue_event.wait(0.05)
                self._queue_event.clear()
                continue
            try:
                self._prefill_one(req)
            finally:
                with self._transit_lock:
                    self._transit -= 1

    def _prefill_one(self, req: Request):
        """One dequeued request (plus fanout members): run the prefill
        and hand (request, cache, first token) entries to the engine.
        Runs with the transit count held by _prefill_loop."""
        self.metrics.set_gauge("tpu_serving_queue_depth", self.queue_depth)
        members = [req] + list(req.fanout or [])
        with self._fanout_lock:
            self._queued_fanout -= len(members) - 1
        live = [r for r in members if not r.future.cancelled()]
        self.metrics.incr("tpu_serving_cancelled",
                          len(members) - len(live))
        if not live:
            return  # every caller gave up while queued
        dequeued = self._perf()
        for r in live:
            r.dequeued_at = dequeued
            self.metrics.observe("tpu_serving_queue_wait_seconds",
                                 dequeued - r.submitted_at)
        single = None
        try:
            # fanout groups need one bindable cache PER member — a paged
            # run's pages can only ever belong to one slot, so groups ride
            # the dense-scratch route
            last_logits, single, matched = self._prefill_tokens(
                req.prompt, req.adapter_id, single_only=len(live) > 1)
            prefill_done = self._perf()
            for r in live:
                r.prefill_done_at = prefill_done
                r.matched_prefix_tokens = matched
            # one prefill, one ready entry PER live member: each samples
            # its own first token from the shared last-position logits
            entries = []
            for r in live:
                keys = self._row_keys(jnp.asarray([r.seed], jnp.uint32),
                                      jnp.asarray([0], jnp.int32))
                row_logits = last_logits
                if r.logit_bias:
                    brow = _bias_row(r.logit_bias, self.cfg.vocab_size)
                    row_logits = (row_logits.astype(jnp.float32)
                                  + jnp.asarray(brow)[None, :])
                # penalties: OpenAI's published formula counts tokens
                # SAMPLED DURING GENERATION only (vLLM likewise) — at
                # the first token nothing has been generated, so no
                # penalty applies here; _admit seeds the slot's counts
                # from the first token alone (ADVICE r4: prompt-seeded
                # counts penalized long-prompt requests on an endpoint
                # advertised as OpenAI-compatible)
                first = int(_sample(row_logits, keys, [r.temperature],
                                    [r.top_k], [r.top_p])[0])
                first_lp = None
                if r.logprobs:
                    # from the distribution actually sampled (biased
                    # when logit_bias is set; NEVER penalized — counts
                    # cover generated tokens only and none exist yet)
                    first_lp = float(jax.nn.log_softmax(
                        row_logits[0].astype(jnp.float32))[first])
                entries.append((r, single, first, first_lp))
        except Exception as exc:  # noqa: BLE001 — poisoned prompt only
            log.exception("prefill of %s failed", req.rid)
            self.metrics.incr("tpu_serving_prefill_errors")
            if isinstance(single, _PagedRun):
                # the run completed but first-token sampling failed: its
                # page references must not outlive the request
                with self._prefix_lock:
                    single.store.release(single.pages)
            for r in live:
                _fail_future(r.future, exc)
            return
        for entry in entries:
            while not self._stop.is_set():
                try:
                    self._ready.put(entry, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def _admit(self) -> bool:
        """Insert ready-made prefilled caches into free slots (cheap donated
        update — the engine thread never runs a prefill itself)."""
        admitted = False
        for slot_id, slot in enumerate(self._slots):
            if slot.request is not None:
                continue
            # pop + transit-count under one lock (see drained): between
            # this pop and slot.request below the request is in neither a
            # queue nor a slot
            with self._transit_lock:
                try:
                    req, single, first, first_lp = self._ready.get_nowait()
                except queue.Empty:
                    break
                self._transit += 1
            try:
                self._admit_into_slot(slot_id, slot, req, single, first,
                                      first_lp)
            finally:
                with self._transit_lock:
                    self._transit -= 1
            admitted = True
            # a failed paged bind (pool exhausted) leaves the slot FREE —
            # the request was already failed; _finished would deref None
            if slot.request is not None and self._finished(slot):
                self._complete(slot_id, slot)
        self.metrics.set_gauge("tpu_serving_active_slots", self.active_slots)
        self._update_kv_gauge()
        return admitted

    def _bind_paged_slot(self, slot_id: int, slot: _Slot,
                         req: Request, single) -> bool:
        """Build the slot's page-table row (paged decode loop). A
        _PagedRun (paged-native prefill) transfers WHOLESALE: the run's
        pages — references and all — become the slot's, no trie match,
        no allocation, no fill_pages copy (this is the admission half of
        the hot path the dense scratch cache vanished from). A dense
        single cache (fanout members, the pool-exhausted fallback,
        paged_prefill=False) takes the classic route: reference the
        prompt's cached full pages ZERO-COPY (the prefill thread's
        insert already wrote them; shared pages are read-only — decode
        writes only ever land in the slot's private tail), allocate
        private pages for whatever the trie doesn't hold, and fill those
        from the prefilled single cache. Returns False (request failed,
        slot stays free) when the pool can't supply the tail pages."""
        from .kv_manager import PoolExhausted
        store = self._kv_store
        if isinstance(single, _PagedRun):
            if single.store is not store:
                # the engine recovered mid-flight: the run's pages died
                # with the discarded arena — there is no KV to bind
                _fail_future(req.future, RuntimeError(
                    f"engine recovered while {req.rid} was in flight; "
                    "its prefilled pages were discarded — retry"))
                self.metrics.incr("tpu_serving_admission_rejected")
                return False
            slot.pages = list(single.pages)
            slot.kv_len = single.kv_len
            slot.table_len = len(single.pages)
            row = self._page_tables_np[slot_id]
            row[:] = 0
            row[:len(slot.pages)] = slot.pages
            return True
        t = self.sc.kv_page_tokens
        n_prompt = len(req.prompt)
        with self._prefix_lock:
            m = store.match_full(req.adapter_id, req.prompt)
            covered = m.matched_tokens
            n_tail = -(-(n_prompt - covered) // t)
            try:
                tail = store.alloc_run(n_tail) if n_tail else []
            except PoolExhausted as exc:
                store.release(m.pages)
                _fail_future(req.future, EngineOverloaded(
                    f"KV pool exhausted admitting {req.rid}: {exc}; "
                    "retry later or raise kv_pool_pages"))
                self.metrics.incr("tpu_serving_admission_rejected")
                return False
            if tail:
                store.fill_pages(single, tail, covered)
            slot.pages = list(m.pages) + tail
            slot.kv_len = n_prompt
            slot.table_len = len(slot.pages)
        row = self._page_tables_np[slot_id]
        row[:] = 0
        row[:len(slot.pages)] = slot.pages
        return True

    def _admit_into_slot(self, slot_id: int, slot: _Slot, req: Request,
                         single: Params, first: int, first_lp):
        """Insert one prefilled cache into a free slot; runs with the
        transit count held by _admit. Paged loop: the slot references
        shared arena pages instead of receiving a contiguous copy."""
        if self._paged_loop:
            if not self._bind_paged_slot(slot_id, slot, req, single):
                return
        else:
            self._cache = self._insert(self._cache, single,
                                       jnp.asarray(slot_id, jnp.int32))
        self._tokens = self._tokens.at[slot_id].set(first)
        self._slot_adapter[slot_id] = req.adapter_id
        self._slot_seed[slot_id] = req.seed
        self._slot_draws[slot_id] = 1  # draw 0 was the prefill token
        if _penalized(req):
            # counts cover GENERATED tokens only (OpenAI/vLLM
            # semantics): the slot starts from just the first sampled
            # token — the prompt never contributes
            if self._tok_counts is None:
                self._tok_counts = jnp.zeros(
                    (self.sc.slots, self.cfg.vocab_size), jnp.int32)
            row = np.zeros((self.cfg.vocab_size,), np.int32)
            row[first] += 1
            self._tok_counts = _set_count_row(
                self._tok_counts, jnp.asarray(slot_id),
                jnp.asarray(row))
        elif self._tok_counts is not None:
            # a stale penalized row must not leak into this request
            self._tok_counts = _set_count_row(
                self._tok_counts, jnp.asarray(slot_id),
                jnp.zeros((self.cfg.vocab_size,), jnp.int32))
        if req.logit_bias:
            if self._logit_bias is None:
                self._logit_bias = jnp.zeros(
                    (self.sc.slots, self.cfg.vocab_size), jnp.float32)
            self._logit_bias = _set_count_row(
                self._logit_bias, jnp.asarray(slot_id),
                jnp.asarray(_bias_row(req.logit_bias,
                                      self.cfg.vocab_size)))
        elif self._logit_bias is not None:
            self._logit_bias = _set_count_row(
                self._logit_bias, jnp.asarray(slot_id),
                jnp.zeros((self.cfg.vocab_size,), jnp.float32))
        slot.request = req
        slot.generated = [first]
        slot.logprobs = [first_lp] if first_lp is not None else []
        slot.remaining = req.max_new_tokens - 1
        slot.last_token = first
        slot.bigram_index = {}
        slot.indexed_upto = 0
        slot.stop_tail = []
        slot.stop_tail_upto = 0
        # the first token becomes caller-visible HERE (the prefill
        # thread sampled it, but _emit below is when it streams), so
        # this is the honest TTFT instant
        now = self._perf()
        req.first_token_at = now
        slot.last_emit_at = now
        # exemplar: the tail TTFT bucket links straight to a replayable
        # trace (/debug/traces), fleet-wide once heartbeats merge it
        self.metrics.observe("tpu_serving_ttft_seconds",
                             now - req.submitted_at,
                             exemplar=req.trace_id or None)
        self._emit(slot, first)
        self.metrics.incr("tpu_serving_admitted")

    def _propose(self, slot: _Slot, k: int) -> list[int]:
        """Prompt-lookup drafting: find the latest prior occurrence of the
        context's final bigram and propose the k tokens that followed it —
        free accuracy on repetitive spans (code, quotes, lists). Falls back
        to repeating the last token (wrong guesses only cost the slack the
        verify pass already paid for).

        The bigram index is maintained INCREMENTALLY (amortized O(1) per
        committed token): a per-step rescan would be O(context) host-side
        Python inside the engine loop — at 32k context that dominates the
        step. Latest occurrence wins, matching the original backward scan
        (which stopped at i <= len-3, hence the n-3 indexing bound)."""
        prompt = slot.request.prompt
        np_ = len(prompt)
        gen = slot.generated
        n = np_ + len(gen)

        def tok(p: int) -> int:
            return prompt[p] if p < np_ else gen[p - np_]

        idx = slot.bigram_index
        while slot.indexed_upto <= n - 3:
            i = slot.indexed_upto
            idx[(tok(i), tok(i + 1))] = i
            slot.indexed_upto += 1
        draft: list[int] = []
        if n >= 3:
            i = idx.get((tok(n - 2), tok(n - 1)))
            if i is not None:
                draft = [tok(p) for p in range(i + 2, min(i + 2 + k, n))]
        last = tok(n - 1)
        while len(draft) < k:
            draft.append(last)
        return draft[:k]

    def _decode_once_speculative(self) -> bool:
        """One verify pass over [last_token, draft...]: greedy slots commit
        the matched prefix plus one corrected token; sampled slots commit 1.
        Returns False (deferring to the plain path) when no active slot is
        greedy — a (k+1)-wide verify would then be pure overhead."""
        k = self.sc.speculate_k
        slots = self._slots
        b = len(slots)
        active = [s.request is not None for s in slots]
        # penalized slots never K-commit: every committed token changes the
        # next token's penalties, so a K-wide greedy run is stale after 1
        if not any(active[i] and slots[i].request.temperature <= 0.0
                   and not _logit_modded(slots[i].request) for i in range(b)):
            return False
        rec = self.recorder
        if rec is not None:
            rec.step_begin()
        active_mask = jnp.asarray(active)
        toks_in = np.zeros((b, k + 1), np.int32)
        n_greedy = 0
        for i, slot in enumerate(slots):
            if not active[i]:
                continue
            toks_in[i, 0] = slot.last_token
            if (slot.request.temperature <= 0.0
                    and not _logit_modded(slot.request)):
                toks_in[i, 1:] = self._propose(slot, k)
                n_greedy += 1
            else:
                toks_in[i, 1:] = slot.last_token  # placeholder, never checked
        if rec is not None:
            rec.mark("schedule")
        logits, self._cache = self._verify(
            self.params, jnp.asarray(toks_in), self._cache, active_mask,
            self._adapters,
            None if self._adapters is None
            else jnp.asarray(self._slot_adapter.copy()))
        if rec is not None:
            rec.mark("kernel")
        greedy_np = np.asarray(jnp.argmax(logits, axis=-1))   # (B, K+1)
        # sampled slots draw token 1 from the same distribution decode_step
        # would have produced (logits[:, 0])
        reqs = [s.request for s in slots]
        temps = [r.temperature if r else 0.0 for r in reqs]
        # verify_step logits are f32 by contract, so these lp reductions are
        # full-precision; gate each on the slot kind that actually reads it
        greedy_lp = None
        if any(r is not None and r.logprobs and r.temperature <= 0.0
               and not _logit_modded(r) for r in reqs):
            # lp of the argmax token = max - logsumexp, no (V,) gather
            greedy_lp = np.asarray(jnp.max(logits, axis=-1)
                                   - jax.nn.logsumexp(logits, axis=-1))
        sampled_np = sampled_lp = None
        if any(t > 0.0 for t in temps) or any(_logit_modded(r)
                                              for r in reqs):
            l0 = self._maybe_penalize(logits[:, 0], reqs)
            sampled_np = np.asarray(self._sample_batch(
                l0, temps,
                [r.top_k if r else 0 for r in reqs],
                [r.top_p if r else 1.0 for r in reqs]))
            if any(r is not None and r.logprobs
                   and (r.temperature > 0.0 or _logit_modded(r))
                   for r in reqs):
                logp0 = jax.nn.log_softmax(l0.astype(jnp.float32), axis=-1)
                sampled_lp = np.asarray(jnp.take_along_axis(
                    logp0, jnp.asarray(sampled_np)[:, None], axis=-1)[:, 0])
            self._bump_penalty_counts(reqs, sampled_np)
        self.metrics.incr("tpu_serving_spec_proposed", k * n_greedy)

        advance = np.zeros((b,), np.int32)
        accepted_total = 0
        if rec is not None:
            rec.mark("sample")
        step_now = self._perf()
        for i, slot in enumerate(slots):
            if not active[i]:
                continue
            greedy_slot = (slot.request.temperature <= 0.0
                           and not _logit_modded(slot.request))
            if greedy_slot:
                committed = []
                for j in range(k + 1):
                    g = int(greedy_np[i, j])
                    committed.append(g)
                    if j >= k or g != int(toks_in[i, j + 1]):
                        break  # mismatch: g is the corrected token
            else:
                committed = [int(sampled_np[i])]
            # positions idx..idx+m-1 hold KV for toks_in[0..m-1], all of
            # which are now committed (m-1 matched drafts + the last token)
            appended = 0
            for jc, tok in enumerate(committed):
                if slot.request is None:
                    break  # finished mid-run (eos / budget)
                slot.generated.append(tok)
                if slot.request.logprobs:
                    slot.logprobs.append(
                        float(greedy_lp[i, jc]) if greedy_slot
                        else float(sampled_lp[i]))
                slot.last_token = tok
                slot.remaining -= 1
                appended += 1
                self._emit(slot, tok)
                self.total_generated += 1
                if self._finished(slot):
                    self._complete(i, slot)
            advance[i] = appended
            self._observe_itl(slot, appended, step_now)
            if greedy_slot and appended > 1:
                # accepted = drafts actually CONSUMED (an early finish must
                # not inflate the exported acceptance rate)
                self.metrics.incr("tpu_serving_spec_accepted", appended - 1)
                accepted_total += appended - 1
        idx = self._cache["index"]
        self._cache = dict(self._cache)
        self._cache["index"] = idx + jnp.asarray(advance)
        self._tokens = jnp.asarray([s.last_token for s in slots], jnp.int32)
        self.metrics.incr("tpu_serving_decode_steps")
        self._observe_step(sum(1 for a in active if a))
        if rec is not None:
            rec.step_end(
                mode="spec_verify", active=sum(1 for a in active if a),
                draining=self._draining.is_set(), paged=False, spec_k=k,
                adapters=int((self._slot_adapter != 0).sum()),
                tokens=int(advance.sum()),
                rids=[s.request.rid for s in slots
                      if s.request is not None],
                spec={"proposed": k * n_greedy,
                      "accepted": accepted_total, "rolled_back_pages": 0})
        return True

    def _observe_itl(self, slot: _Slot, appended: int, now: float):
        """Per-token inter-token latency: the step gap spread evenly over
        the tokens it committed (speculative steps commit several at once —
        the client-visible stream sees them back to back, but the SLO
        series must count one sample per token)."""
        if not appended:
            return
        if slot.last_emit_at:
            per_tok = (now - slot.last_emit_at) / appended
            for _ in range(appended):
                self.metrics.observe("tpu_serving_inter_token_seconds",
                                     per_tok)
        slot.last_emit_at = now

    def _observe_step(self, n_active: int):
        """Per-decode-step batch health: slot-fill fraction + KV occupancy."""
        self.metrics.observe("tpu_serving_batch_utilization",
                             n_active / max(1, self.sc.slots))
        self._update_kv_gauge()
        # compile detection for the POLLED (shared module-level) jits —
        # one dict-len read per attached fn per step
        self.watchdog.poll()

    def _arena_step_stats(self) -> Optional[dict]:
        """O(1) arena occupancy for a step record: live counts from the
        pool, trie-shared from the last gauge refresh (walking refcounts
        per step would cost more than the step), plus the window-ring
        pages recycled since the last record."""
        store = self._kv_store
        if store is None:
            return None
        recycled, self._ring_recycled = self._ring_recycled, 0
        stats = self._page_stats
        return {"pages_total": store.pool.n_pages,
                "pages_free": store.pool.free_count,
                "pages_shared": stats["pages_shared"] if stats else 0,
                "ring_recycled": recycled}

    def debug_steps(self, n: int = 64) -> dict:
        """The GET /debug/steps payload: the step-record tail + rollup
        (when the recorder is on) and the watchdog's per-fn compile
        counts (always)."""
        out = ({"enabled": False} if self.recorder is None
               else self.recorder.snapshot(n))
        out["recompiles"] = self.watchdog.snapshot()
        return out

    def _update_kv_gauge(self):
        self.metrics.set_gauge("tpu_serving_kv_cache_tokens", sum(
            len(s.request.prompt) + len(s.generated)
            for s in self._slots if s.request is not None))

    def _decode_once(self):
        if self._paged_loop:
            return self._decode_once_paged()
        if self._verify is not None and self._decode_once_speculative():
            return
        rec = self.recorder
        if rec is not None:
            rec.step_begin()
        active_mask = jnp.asarray([s.request is not None for s in self._slots])
        if rec is not None:
            rec.mark("schedule")
        logits, self._cache = self._decode(
            self.params, self._tokens, self._cache, active_mask,
            self._adapters,
            None if self._adapters is None
            else jnp.asarray(self._slot_adapter.copy()))
        if rec is not None:
            rec.mark("kernel")
        self._commit_decode(logits)

    def _grow_slot_table(self, slot_id: int, slot: _Slot, need: int) -> bool:
        """Extend the slot's page table to cover positions
        [0, kv_len + need) before a step writes them: a slot whose next
        write positions cross into fresh pages gets PRIVATE pages —
        shared prefix pages are never written (allocate-on-write COW
        discipline). Sliding-window slots RECYCLE instead of allocating
        once the table is _win_pages deep: entry j - _win_pages'
        positions are entirely behind the window by the time entry j is
        written (the paged kernels skip out-of-window entries, so the
        aliased table rows are never read), making a slot's steady-state
        residency O(window) pages — the ring cache's memory win, paged.
        Returns False when the pool is exhausted: THIS request fails and
        the engine (and every other slot) keeps serving — prefix caching
        degrades, decode capacity does not crash."""
        from .kv_manager import PoolExhausted
        store = self._kv_store
        t = self.sc.kv_page_tokens
        row = self._page_tables_np[slot_id]
        while slot.table_len * t < slot.kv_len + need:
            j = slot.table_len
            with self._prefix_lock:
                try:
                    if self._window is not None and j >= self._win_pages:
                        old = int(row[j - self._win_pages])
                        if store.pool.refcount(old) == 1:
                            # only this slot holds it: reuse in place
                            page = old
                        else:
                            # shared with the trie (or an in-flight
                            # match): allocate-on-write — the slot
                            # swaps its reference for a private page,
                            # the shared copy stays cached
                            page = store.alloc_run(1)[0]
                            store.pool.unref(old)
                            slot.pages.remove(old)
                            slot.pages.append(page)
                        # engine-thread-only counter, drained into the
                        # next step record (_arena_step_stats)
                        self._ring_recycled += 1
                    else:
                        page = store.alloc_run(1)[0]
                        slot.pages.append(page)
                except PoolExhausted as exc:
                    store.release(slot.pages)
                    slot.pages = []
                    slot.kv_len = 0
                    slot.table_len = 0
                    self._page_tables_np[slot_id][:] = 0
                    req, slot.request = slot.request, None
                    _fail_future(req.future, RuntimeError(
                        f"KV pool exhausted mid-decode for {req.rid}: "
                        f"{exc}"))
                    return False
            row[j] = page
            slot.table_len = j + 1
        return True

    def _decode_once_paged(self):
        """One decode step on per-slot page tables over the shared arena
        (paged_decode_step): matched prefix pages and adopted handoff
        pages are attended IN PLACE — no per-slot contiguous copy exists
        anywhere. The step's dispatch rides _prefix_lock because it
        DONATES the arena; the lock covers dispatch only (async), never
        the device wait, so prefill-thread arena ops interleave at
        dispatch granularity. Speculative engines verify k+1 drafts
        through the multi-token kernels first
        (_decode_once_speculative_paged); windowed slots skip that (page
        recycling aliases table entries, which rollback can't untangle)
        and decode one token at a time — still token-identical, just
        without the free drafts."""
        if (self._paged_verify is not None and self._window is None
                and self._decode_once_speculative_paged()):
            return
        rec = self.recorder
        if rec is not None:
            rec.step_begin()
        store = self._kv_store
        for slot_id, slot in enumerate(self._slots):
            if slot.request is None:
                continue
            self._grow_slot_table(slot_id, slot, 1)
        active = [s.request is not None for s in self._slots]
        if not any(active):
            self.metrics.set_gauge("tpu_serving_active_slots", 0)
            return
        lengths = jnp.asarray([s.kv_len for s in self._slots], jnp.int32)
        page_tables = jnp.asarray(self._page_tables_np)
        if rec is not None:
            rec.mark("schedule")
        with self._prefix_lock:
            logits, arena, _ = self._paged_step(
                self.params, self._tokens, store.arena, page_tables,
                lengths, jnp.asarray(active), self._adapters,
                None if self._adapters is None
                else jnp.asarray(self._slot_adapter.copy()))
            store.arena = arena
        if rec is not None:
            rec.mark("kernel")
        self._commit_decode(logits)

    def _decode_once_speculative_paged(self) -> bool:
        """Speculative verification on the paged loop (ISSUE 14): one
        multi-token pass over [last_token, draft...] through per-slot
        page tables (paged_verify_step). Greedy slots commit the matched
        prefix plus one corrected token; sampled slots ride along with
        n_tokens = 1 — their KV write and their logits[:, 0] are exactly
        the plain step's. Rejection rollback is page-native: the
        committed length simply stops where the first mismatch landed
        and the table entries past it DROP back to the pool — the
        append-only pages need none of the ring-invariant contortions
        the contiguous speculative path carries. Returns False
        (deferring to the plain paged step) when no active slot is
        greedy — a (k+1)-wide verify would then be pure overhead."""
        k = self.sc.speculate_k
        slots = self._slots
        b = len(slots)
        t = self.sc.kv_page_tokens
        store = self._kv_store
        active = [s.request is not None for s in slots]

        def greedy(i: int) -> bool:
            return (active[i] and slots[i].request is not None
                    and slots[i].request.temperature <= 0.0
                    and not _logit_modded(slots[i].request))

        if not any(greedy(i) for i in range(b)):
            return False
        rec = self.recorder
        if rec is not None:
            rec.step_begin()
        # table growth BEFORE the step: a greedy slot may write k+1 rows
        # this pass, a sampled slot exactly 1
        for i, slot in enumerate(slots):
            if not active[i]:
                continue
            self._grow_slot_table(i, slot, k + 1 if greedy(i) else 1)
        active = [s.request is not None for s in slots]  # growth may fail
        if not any(active):
            self.metrics.set_gauge("tpu_serving_active_slots", 0)
            return True
        toks_in = np.zeros((b, k + 1), np.int32)
        n_tokens = np.zeros((b,), np.int32)
        n_greedy = 0
        for i, slot in enumerate(slots):
            if not active[i]:
                continue
            toks_in[i, 0] = slot.last_token
            if greedy(i):
                toks_in[i, 1:] = self._propose(slot, k)
                n_tokens[i] = k + 1
                n_greedy += 1
            else:
                toks_in[i, 1:] = slot.last_token  # placeholder, never checked
                n_tokens[i] = 1
        lengths = jnp.asarray([s.kv_len for s in slots], jnp.int32)
        page_tables = jnp.asarray(self._page_tables_np)
        if rec is not None:
            rec.mark("schedule")
        with self._prefix_lock:
            logits, arena = self._paged_verify(
                self.params, jnp.asarray(toks_in), store.arena,
                page_tables, lengths, jnp.asarray(active), self._adapters,
                None if self._adapters is None
                else jnp.asarray(self._slot_adapter.copy()),
                jnp.asarray(n_tokens))
            store.arena = arena
        if rec is not None:
            rec.mark("kernel")
        greedy_np = np.asarray(jnp.argmax(logits, axis=-1))   # (B, K+1)
        reqs = [s.request for s in slots]
        temps = [r.temperature if r else 0.0 for r in reqs]
        # paged_verify_step logits are f32 by contract, so these lp
        # reductions are full-precision; gate each on the slot kind that
        # actually reads it
        greedy_lp = None
        if any(r is not None and r.logprobs and r.temperature <= 0.0
               and not _logit_modded(r) for r in reqs):
            greedy_lp = np.asarray(jnp.max(logits, axis=-1)
                                   - jax.nn.logsumexp(logits, axis=-1))
        sampled_np = sampled_lp = None
        if any(tm > 0.0 for tm in temps) or any(_logit_modded(r)
                                                for r in reqs):
            l0 = self._maybe_penalize(logits[:, 0], reqs)
            sampled_np = np.asarray(self._sample_batch(
                l0, temps,
                [r.top_k if r else 0 for r in reqs],
                [r.top_p if r else 1.0 for r in reqs]))
            if any(r is not None and r.logprobs
                   and (r.temperature > 0.0 or _logit_modded(r))
                   for r in reqs):
                logp0 = jax.nn.log_softmax(l0.astype(jnp.float32), axis=-1)
                sampled_lp = np.asarray(jnp.take_along_axis(
                    logp0, jnp.asarray(sampled_np)[:, None], axis=-1)[:, 0])
            self._bump_penalty_counts(reqs, sampled_np)
        self.metrics.incr("tpu_serving_spec_proposed", k * n_greedy)

        if rec is not None:
            rec.mark("sample")
        step_now = self._perf()
        rolled_back = 0
        accepted_total = 0
        committed_total = 0
        for i, slot in enumerate(slots):
            if not active[i]:
                continue
            greedy_slot = greedy(i)
            if greedy_slot:
                committed = []
                for j in range(k + 1):
                    g = int(greedy_np[i, j])
                    committed.append(g)
                    if j >= k or g != int(toks_in[i, j + 1]):
                        break  # mismatch: g is the corrected token
            else:
                committed = [int(sampled_np[i])]
            appended = 0
            for jc, tok in enumerate(committed):
                if slot.request is None:
                    break  # finished mid-run (eos / budget)
                slot.generated.append(tok)
                if slot.request.logprobs:
                    slot.logprobs.append(
                        float(greedy_lp[i, jc]) if greedy_slot
                        else float(sampled_lp[i]))
                slot.last_token = tok
                slot.remaining -= 1
                appended += 1
                # the step wrote row jc's KV at position kv_len:
                # committing token jc commits that row
                slot.kv_len += 1
                self._emit(slot, tok)
                self.total_generated += 1
                if self._finished(slot):
                    self._complete(i, slot)
            self._observe_itl(slot, appended, step_now)
            committed_total += appended
            if greedy_slot and appended > 1:
                # accepted = drafts actually CONSUMED (an early finish must
                # not inflate the exported acceptance rate)
                self.metrics.incr("tpu_serving_spec_accepted", appended - 1)
                accepted_total += appended - 1
            if slot.request is None:
                continue  # _complete released every page already
            # rejection rollback: table entries past the committed length
            # hold only rejected rows — drop them back to the pool. All
            # fresh private pages (window is None on this path, so
            # entries map 1:1 to distinct pages, and shared prefix pages
            # all sit below the committed length).
            keep = -(-slot.kv_len // t)
            if slot.table_len > keep:
                row = self._page_tables_np[i]
                dropped = [int(row[j]) for j in range(keep, slot.table_len)]
                for page in dropped:
                    slot.pages.remove(page)
                row[keep:slot.table_len] = 0
                slot.table_len = keep
                with self._prefix_lock:
                    store.release(dropped)
                rolled_back += len(dropped)
        if rolled_back:
            self.metrics.incr(
                "tpu_serving_paged_speculative_rollback_pages", rolled_back)
        self._tokens = jnp.asarray([s.last_token for s in slots], jnp.int32)
        self.metrics.incr("tpu_serving_decode_steps")
        self.metrics.incr("tpu_serving_paged_speculative_steps")
        self._observe_step(sum(1 for a in active if a))
        if rec is not None:
            rec.step_end(
                mode="spec_verify", active=sum(1 for a in active if a),
                draining=self._draining.is_set(), paged=True, spec_k=k,
                adapters=int((self._slot_adapter != 0).sum()),
                tokens=committed_total,
                rids=[s.request.rid for s in slots
                      if s.request is not None],
                arena=self._arena_step_stats(),
                spec={"proposed": k * n_greedy,
                      "accepted": accepted_total,
                      "rolled_back_pages": rolled_back})
        return True

    def _commit_decode(self, logits):
        """Host-side half of a decode step, shared by the contiguous and
        paged loops: per-slot sampling (temperature/top-k/top-p,
        penalties, logit_bias), logprobs, stream emission, stop checks,
        and the step metrics."""
        reqs = [s.request for s in self._slots]
        temps = [r.temperature if r else 0.0 for r in reqs]
        ks = [r.top_k if r else 0 for r in reqs]
        ps = [r.top_p if r else 1.0 for r in reqs]
        logits = self._maybe_penalize(logits, reqs)
        # sample per slot (temperature / top-k / top-p can differ per request)
        next_np = np.asarray(self._sample_batch(logits, temps, ks, ps))
        self._bump_penalty_counts(reqs, next_np)
        lp_np = None
        if any(r is not None and r.logprobs for r in reqs):
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            lp_np = np.asarray(jnp.take_along_axis(
                logp, jnp.asarray(next_np)[:, None], axis=-1)[:, 0])
        rec = self.recorder
        if rec is not None:
            rec.mark("sample")
        step_now = self._perf()
        n_active = 0
        for slot_id, slot in enumerate(self._slots):
            if slot.request is None:
                continue
            n_active += 1
            if self._paged_loop:
                # the step wrote this slot's input token's KV at kv_len
                slot.kv_len += 1
            tok = int(next_np[slot_id])
            slot.generated.append(tok)
            if slot.request.logprobs and lp_np is not None:
                slot.logprobs.append(float(lp_np[slot_id]))
            slot.last_token = tok
            slot.remaining -= 1
            self._emit(slot, tok)
            self._observe_itl(slot, 1, step_now)
            self.total_generated += 1
            if self._finished(slot):
                self._complete(slot_id, slot)
        self._tokens = jnp.asarray(next_np, jnp.int32)
        self.metrics.incr("tpu_serving_decode_steps")
        self._observe_step(n_active)
        if rec is not None:
            rec.step_end(
                mode="decode", active=n_active,
                draining=self._draining.is_set(),
                paged=self._paged_loop, spec_k=0,
                adapters=int((self._slot_adapter != 0).sum()),
                tokens=n_active,
                rids=[s.request.rid for s in self._slots
                      if s.request is not None],
                arena=self._arena_step_stats())

    def _maybe_penalize(self, logits: jax.Array, reqs) -> jax.Array:
        """Apply OpenAI presence/frequency penalties and logit_bias to
        (B, V) logits for the slots that asked for them; identity (and
        zero device work) when nobody did."""
        if self._tok_counts is not None and any(_penalized(r) for r in reqs):
            pres = jnp.asarray(
                [r.presence_penalty if r else 0.0 for r in reqs], jnp.float32)
            freq = jnp.asarray(
                [r.frequency_penalty if r else 0.0 for r in reqs], jnp.float32)
            logits = _apply_penalties(logits, self._tok_counts, pres, freq)
        if self._logit_bias is not None and any(
                r is not None and r.logit_bias for r in reqs):
            logits = logits.astype(jnp.float32) + self._logit_bias
        return logits

    def _bump_penalty_counts(self, reqs, next_np):
        """Record this step's committed token for each penalized slot
        (fixed shapes: one jitted scatter regardless of who is penalized)."""
        if self._tok_counts is None or not any(_penalized(r) for r in reqs):
            return
        mask = np.asarray([_penalized(r) for r in reqs])
        self._tok_counts = _bump_counts(
            self._tok_counts, jnp.asarray(np.asarray(next_np, np.int32)),
            jnp.asarray(mask))

    def _sample_batch(self, logits: jax.Array, temps: list[float],
                      top_ks: Optional[list[int]] = None,
                      top_ps: Optional[list[float]] = None) -> jax.Array:
        """Per-slot keys from (request seed, draws so far); one draw is
        consumed per call for every slot (greedy slots ignore theirs).

        The .copy() calls are LOAD-BEARING: jax's CPU backend may zero-copy
        alias a numpy input as the device buffer, so handing it the live
        bookkeeping arrays (mutated by += below / _admit) lets the in-place
        write race the still-in-flight async computation — a one-draw slip
        that breaks seed reproducibility once in ~dozens of requests."""
        keys = self._row_keys(jnp.asarray(self._slot_seed.copy()),
                              jnp.asarray(self._slot_draws.copy()))
        self._slot_draws += 1
        return _sample(logits, keys, temps, top_ks, top_ps)

    def _emit(self, slot: _Slot, tok: int):
        """Stream a token to the requester; a raising callback means the
        client is gone — finish the request now with what it has."""
        req = slot.request
        if req is None or req.on_token is None:
            return
        try:
            req.on_token(tok)
        except Exception:  # noqa: BLE001 — client callback, not engine state
            log.info("stream callback failed for %s; cancelling", req.rid)
            req.on_token = None
            slot.remaining = 0
            self.metrics.incr("tpu_serving_stream_cancelled")

    def _finished(self, slot: _Slot) -> bool:
        if slot.request.future.cancelled():
            return True  # caller gave up (timeout/disconnect): free the slot
        if slot.remaining <= 0 or slot.last_token == self.sc.eos_token:
            return True
        gen = slot.generated
        if any(len(s) <= len(gen) and gen[-len(s):] == s
               for s in slot.request.stop):
            return True
        if slot.request.stop_texts:
            # BPE-exact: a stop string straddling a token boundary never
            # equals a generated token tail, but it IS in the decoded text.
            # Keep a running TAIL of token ids trimmed by DECODED length:
            # the front is popped only while the rest still decodes to >=
            # max-stop-chars + slack, so zero-char specials can't shrink
            # the effective lookback below a stop's length, and the
            # detokenizer's first-token artifact (sentencepiece space
            # stripping) stays >= slack chars away from where any NEW
            # match (which must end in the newest token) can sit. Cost
            # stays O(stop_len) decode per step, not O(generated²)/request.
            need = max(len(s) for s in slot.request.stop_texts) + 8
            tail = slot.stop_tail
            tail.extend(gen[slot.stop_tail_upto:])
            slot.stop_tail_upto = len(gen)
            while len(tail) > 1 and (
                    len(tail) > 4 * need  # hard token cap: a degenerate
                    # run of all-zero-char specials must not grow the tail
                    # (and this decode) without bound in the shared loop
                    or len(self._decode_fn(tail[1:])) >= need):
                tail.pop(0)
            text = self._decode_fn(tail)
            return any(s in text for s in slot.request.stop_texts)
        return False

    def _record_request_spans(self, req: Request, slot: _Slot,
                              latency: float, cost: Optional[dict] = None):
        """The request's span tree, recorded retroactively from the
        timestamps the threads already keep (no live span objects cross the
        submit/prefill/engine threads). Children are CONTIGUOUS — queue-wait
        (submit->prefill dequeue), prefill (dequeue->prefill done), decode
        (prefill done->finish, ready-queue wait included) — so their
        durations sum to the recorded request latency."""
        tr = self.tracer
        now_perf = self._perf()
        now_wall = tr.clock()

        def wall(t_perf: float) -> float:
            return now_wall - (now_perf - t_perf)

        trace_id = req.trace_id or Tracer.new_trace_id()
        root = req.span_id or Tracer.new_span_id()
        end = wall(req.submitted_at + latency)
        ttft = (req.first_token_at - req.submitted_at
                if req.first_token_at else None)
        attrs = {"rid": req.rid, "prompt_tokens": len(req.prompt),
                 "tokens": len(slot.generated),
                 "ttft_s": ttft, "latency_s": latency,
                 "adapter_id": req.adapter_id,
                 # prefix-cache outcome: dashboards join hit-rate
                 # to TTFT per request (the router-affinity payoff)
                 "prefix_hit": req.matched_prefix_tokens > 0,
                 "matched_prefix_tokens": req.matched_prefix_tokens}
        if self.recorder is not None:
            # flight-recorder attribution: how many engine steps this
            # request rode and its even share of their wall/kernel time
            # — the join from a slow request to the step timeline that
            # served it (/debug/steps)
            acc = self.recorder.pop_request(req.rid)
            if acc is not None:
                attrs["decode_steps"] = acc["steps"]
                attrs["step_wall_share_s"] = round(acc["step_wall_s"], 6)
                attrs["step_kernel_share_s"] = round(acc["kernel_s"], 6)
        if cost is not None and self.costmeter is not None:
            # cost attribution (ISSUE 20): dollars + per-phase chip-seconds
            # + KV page-seconds ride the request root span, so a trace
            # waterfall prices itself
            attrs.update(self.costmeter.span_attrs(cost))
        tr.record("serving.request", wall(req.submitted_at), end,
                  trace_id=trace_id, span_id=root,
                  parent_id=req.parent_span_id, attrs=attrs)
        if req.dequeued_at:
            tr.record("serving.queue_wait", wall(req.submitted_at),
                      wall(req.dequeued_at), trace_id=trace_id,
                      parent_id=root, attrs={"rid": req.rid})
        if req.prefill_done_at:
            tr.record("serving.prefill", wall(req.dequeued_at),
                      wall(req.prefill_done_at), trace_id=trace_id,
                      parent_id=root,
                      attrs={"rid": req.rid,
                             "prompt_tokens": len(req.prompt),
                             "matched_prefix_tokens":
                                 req.matched_prefix_tokens})
            tr.record("serving.decode", wall(req.prefill_done_at), end,
                      trace_id=trace_id, parent_id=root,
                      attrs={"rid": req.rid,
                             "tokens": len(slot.generated)})

    def _complete(self, slot_id: int, slot: _Slot):
        req = slot.request
        slot.request = None
        self._slot_adapter[slot_id] = 0
        # KV page-seconds need the slot's page count AT COMPLETION — capture
        # before release empties the list
        pages_end = len(slot.pages)
        if self._paged_loop and slot.pages:
            # drop the slot's references: shared prefix pages stay in the
            # trie for the next hit, private tail pages free immediately.
            # slot.pages holds each DISTINCT physical page once (windowed
            # recycling aliases table entries, never duplicates the list)
            with self._prefix_lock:
                self._kv_store.release(slot.pages)
            slot.pages = []
            slot.kv_len = 0
            slot.table_len = 0
            self._page_tables_np[slot_id][:] = 0
        latency = self._perf() - req.submitted_at
        self.metrics.observe("tpu_serving_request_latency_seconds", latency,
                             exemplar=req.trace_id or None)
        cost = None
        if self.costmeter is not None:
            try:
                cost = self.costmeter.meter_request(
                    req, end_at=req.submitted_at + latency,
                    generated_tokens=len(slot.generated),
                    pages_end=pages_end,
                    page_tokens=self.sc.kv_page_tokens)
            except Exception:  # noqa: BLE001 — metering must never fail a request
                log.exception("cost metering for %s failed", req.rid)
        try:
            self._record_request_spans(req, slot, latency, cost=cost)
        except Exception:  # noqa: BLE001 — tracing must never fail a request
            log.exception("span recording for %s failed", req.rid)
        out = {"rid": req.rid, "tokens": slot.generated,
               "latency_s": latency}
        if req.logprobs:
            out["logprobs"] = slot.logprobs
        try:
            # set_running_or_notify_cancel is the ATOMIC claim: it returns
            # False iff the client's cancel won (a cancel landing between a
            # cancelled() check and set_result would otherwise raise
            # InvalidStateError and trip the whole-engine recovery path)
            if req.future.set_running_or_notify_cancel():
                req.future.set_result(out)
            else:
                self.metrics.incr("tpu_serving_cancelled")
        except Exception:  # noqa: BLE001 — future already resolved elsewhere
            pass
        self.metrics.set_gauge("tpu_serving_active_slots", self.active_slots)
