"""Per-request sampling for the serving engine: seeded PRNG streams,
temperature / top-k / nucleus filtering, OpenAI presence/frequency
penalties and logit_bias.

Everything here is a pure function over (logits, per-row parameters) —
the engine owns the bookkeeping arrays (per-slot seeds/draw counts/token
counts) and calls in with fixed (B,) shapes so nothing recompiles as
requests come and go. Split out of the engine so the sampling math is a
testable unit (and the paged-KV engine rewrite didn't have to carry it)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _row_keys(seeds: jax.Array, draws: jax.Array) -> jax.Array:
    """Per-row PRNG keys from (request seed, samples drawn so far): sampling
    is reproducible PER REQUEST (OpenAI ``seed``) and independent of which
    slot a request lands in or what else shares the batch."""
    def one(s, d):
        return jax.random.fold_in(jax.random.PRNGKey(s), d)
    return jax.vmap(one)(seeds, draws)


def _penalized(r) -> bool:
    return r is not None and (r.presence_penalty != 0.0
                              or r.frequency_penalty != 0.0)


def _bias_row(logit_bias: dict, vocab_size: int) -> np.ndarray:
    """Dense (V,) f32 additive row from an OpenAI logit_bias map — ONE
    construction for the first-token path and the per-slot steady state."""
    row = np.zeros((vocab_size,), np.float32)
    for t, bias in logit_bias.items():
        row[int(t)] = float(bias)
    return row


def _logit_modded(r) -> bool:
    """Penalties or logit_bias: the next token must come from MODIFIED
    logits, so the speculative K-wide greedy commit (which compares raw
    argmaxes) is off the table for these requests."""
    return _penalized(r) or (r is not None and bool(r.logit_bias))


@jax.jit
def _apply_penalties(logits: jax.Array, counts: jax.Array,
                     presence: jax.Array, frequency: jax.Array) -> jax.Array:
    """logits (B, V) minus OpenAI penalties from per-slot token counts
    (B, V): presence once per seen token, frequency per occurrence. Rows
    with zero penalties pass through unchanged (their counts still exist
    but multiply by 0)."""
    c = counts.astype(jnp.float32)
    pen = (presence[:, None] * (c > 0).astype(jnp.float32)
           + frequency[:, None] * c)
    return logits.astype(jnp.float32) - pen


@jax.jit
def _bump_counts(counts: jax.Array, toks: jax.Array,
                 mask: jax.Array) -> jax.Array:
    """counts[i, toks[i]] += 1 where mask[i] — fixed (B,) shapes so the
    per-step update never recompiles."""
    rows = jnp.arange(counts.shape[0])
    return counts.at[rows, toks].add(mask.astype(jnp.int32))


@jax.jit
def _set_count_row(counts: jax.Array, slot: jax.Array,
                   row: jax.Array) -> jax.Array:
    return counts.at[slot].set(row)


def _scaled_and_greedy(logits, temps):
    """Shared head of both sampling kernels (inlines under jit): argmax for
    the per-row greedy override, temperature-scaled f32 logits."""
    greedy = jnp.argmax(logits, axis=-1)
    scaled = (logits / jnp.maximum(temps, 1e-6)[:, None]).astype(jnp.float32)
    return scaled, greedy


@jax.jit
def _sample_plain(logits: jax.Array, keys: jax.Array,
                  temps: jax.Array) -> jax.Array:
    """Unfiltered per-row sampling (no top-k/top-p in the batch): no (B, V)
    sort on the per-token hot loop."""
    scaled, greedy = _scaled_and_greedy(logits, temps)
    sampled = jax.vmap(jax.random.categorical)(keys, scaled)
    return jnp.where(temps > 0.0, sampled, greedy)


@jax.jit
def _sample_filtered(logits: jax.Array, keys: jax.Array, temps: jax.Array,
                     top_ks: jax.Array, top_ps: jax.Array) -> jax.Array:
    v = logits.shape[-1]
    scaled, greedy = _scaled_and_greedy(logits, temps)
    sorted_desc = -jnp.sort(-scaled, axis=-1)              # (B, V) desc
    # top-k threshold: the k-th largest logit (k=0 -> keep all)
    ks = jnp.where(top_ks > 0, top_ks, v)
    thresh_k = jnp.take_along_axis(
        sorted_desc, jnp.clip(ks - 1, 0, v - 1)[:, None], axis=-1)
    # top-p threshold: smallest prefix of the sorted distribution with
    # cumulative mass >= p; "cum before this token < p" keeps >= 1 token
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    before = jnp.cumsum(probs, axis=-1) - probs
    keep = before < top_ps[:, None]
    idx_p = jnp.sum(keep, axis=-1) - 1                     # last kept
    thresh_p = jnp.take_along_axis(sorted_desc, idx_p[:, None], axis=-1)
    thresh = jnp.maximum(thresh_k, thresh_p)
    filtered = jnp.where(scaled >= thresh, scaled, -jnp.inf)
    sampled = jax.vmap(jax.random.categorical)(keys, filtered)
    return jnp.where(temps > 0.0, sampled, greedy)


def _sample(logits: jax.Array, keys: jax.Array, temps: list[float],
            top_ks: Optional[list[int]] = None,
            top_ps: Optional[list[float]] = None) -> jax.Array:
    """Per-row temperature + top-k + nucleus (top-p) sampling with PER-ROW
    PRNG keys (``keys`` (B, 2) from _row_keys). Filters operate on the
    temperature-scaled distribution; the (B, V) sort is cheap at serving
    batch sizes (JetStream does the same).

    Dispatches to JITTED kernels with per-row parameters as ARRAYS — the
    sampler runs once per decode step, and an eager version costs ~10
    separate device executions per step; only the all-greedy / any-filter
    shape of the batch (two variants total) picks the compiled path."""
    if all(t <= 0.0 for t in temps):
        return jnp.argmax(logits, axis=-1)
    b = logits.shape[0]
    t = jnp.asarray(temps, jnp.float32)
    top_ks = top_ks or [0] * b
    top_ps = top_ps or [1.0] * b
    if all(k <= 0 for k in top_ks) and all(p >= 1.0 for p in top_ps):
        return _sample_plain(logits, keys, t)
    return _sample_filtered(logits, keys, t,
                            jnp.asarray(top_ks, jnp.int32),
                            jnp.asarray(top_ps, jnp.float32))
