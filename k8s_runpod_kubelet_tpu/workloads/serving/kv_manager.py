"""Paged KV prefix pool: fixed-size pages in one preallocated HBM arena,
a free-list allocator, and a radix trie of copy-on-write-shared prefix KV.

At millions-of-users scale most requests share long system prompts, and
the fleet router's rendezvous prefix-affinity already concentrates
same-prefix traffic on one replica — this module is where that affinity
pays off. The design (ParvaGPU / vLLM / JetStream lineage):

- **PagePool** — pure host-side bookkeeping: a free list plus per-page
  refcounts over a fixed page count. Pages are never handed out twice
  (the free list is the single source of allocation), and a page returns
  to the free list exactly when its refcount hits zero. Sharing is
  copy-on-write in the allocate-on-write form: shared pages are NEVER
  written in place — readers gather, writers get fresh allocations.
  ``cow()`` is the explicit claim primitive (exclusive owner keeps the
  page, a shared page swaps for a fresh copy): unit-tested here, and the
  write path the zero-copy per-slot page-table decode (ROADMAP item 2's
  engine integration) claims its private tail page through.

- **PrefixTrie** — a radix trie over PAGE-SIZED token chunks, one KV page
  per node, one root per LoRA adapter id (adapter deltas flow into K/V,
  so adapter prefix KV legitimately differs from the base's). ``match``
  walks a prompt's full chunks and returns the shared pages with a
  reference held, so a concurrent eviction can NEVER free a page someone
  is still gathering from — eviction detaches the node and drops the
  trie's reference; the pool frees the page only when the last reader
  releases it. Eviction is LRU over unpinned leaves; ``register_prefix``
  pins its path (never evicted), subsuming the old ``_PrefixEntry``
  registry without pinning whole single-slot caches.

- **PagedKVStore** — the device side: one arena array per KV cache
  section, shaped like the section with (batch -> pages, positions ->
  page_tokens). Works unchanged for plain K/V, int8-quantized K/V
  (scale sections page alongside), and MLA latent caches (c/kr and the
  dense-prefix sections) because it is generic over the section dict.
  ``gather`` copies matched pages into a fresh single-request cache
  (positions 0..matched) so the engine skips exactly that much prefill;
  ``write`` chops a prefilled cache's full pages back into the arena.
  Ring/mixed (``abs_pos``) layouts cannot page — position p lives at
  slot p %% ring and early positions are overwritten by design — so
  registered prefixes there fall back to **DensePrefixStore**, a pinned
  dense-cache registry with the old per-adapter variant semantics.

Thread-safety: PagePool/PrefixTrie/PagedKVStore do no locking of their
own — the engine serializes every call (and every arena read/write, which
matters because ``write`` DONATES the arena buffers) under its
``_prefix_lock``. Docstrings below say so where it is load-bearing.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional


def kv_cache_pspec(name: str, ndim: int):
    """PartitionSpec for one KV-cache section under mesh serving — THE
    layout contract between the engine (_fresh_cache), the paged arena
    (PagedKVStore: same section names, batch axis -> pages, positions ->
    page_tokens, SAME rank) and the AOT evidence tool (tools/aot_check.py
    check_sharded_serving): K/V (L, B, len, h, d) shard the kv-heads axis
    (second-to-last) over ``tensor``; *_scale (L, B, len, h) have heads
    last; index/abs_pos bookkeeping replicates."""
    from jax.sharding import PartitionSpec as P
    from ...parallel.mesh import AXES
    if name in ("index", "abs_pos"):
        return P()
    if name in ("c", "kr", "c_scale", "kr_scale",
                "c_pre", "kr_pre", "c_pre_scale", "kr_pre_scale"):
        # MLA latent cache: NO heads axis — every tensor shard's heads
        # attend over all positions' latents, so the cache replicates.
        # Even replicated it is 8-57x smaller than a tensor-sharded K/V
        # cache (576 B/token at DeepSeek-V2 geometry vs 32k unsharded).
        return P()
    if name.endswith("_scale"):
        return P(*([None] * (ndim - 1) + [AXES.TENSOR]))
    return P(*([None] * (ndim - 2) + [AXES.TENSOR, None]))


class PoolExhausted(RuntimeError):
    """No free page and nothing evictable — the caller stops inserting
    (prefix caching degrades to plain prefill, never an engine error)."""


class PagePool:
    """Free-list page allocator with refcounts. Host bookkeeping only —
    the page PAYLOAD lives in PagedKVStore's arena; a page id is an index
    into it. Not thread-safe: the engine serializes calls under its
    prefix lock."""

    def __init__(self, n_pages: int):
        if n_pages < 1:
            raise ValueError(f"n_pages must be >= 1, got {n_pages}")
        self.n_pages = n_pages
        # LIFO free list: recently-freed pages are re-used first (their
        # arena tiles are the warmest)
        self._free = list(range(n_pages - 1, -1, -1))
        self._refs = [0] * n_pages

    def alloc(self) -> int:
        """One free page at refcount 1; PoolExhausted when empty (the
        free list is the ONLY allocation source, so a page can never be
        handed out twice)."""
        if not self._free:
            raise PoolExhausted(f"all {self.n_pages} KV pages in use")
        page = self._free.pop()
        self._refs[page] = 1
        return page

    def ref(self, page: int) -> None:
        if self._refs[page] <= 0:
            raise ValueError(f"ref of free page {page}")
        self._refs[page] += 1

    def unref(self, page: int) -> bool:
        """Drop one reference; returns True when this freed the page."""
        r = self._refs[page] - 1
        if r < 0:
            raise ValueError(f"unref of free page {page}")
        self._refs[page] = r
        if r == 0:
            self._free.append(page)
            return True
        return False

    def cow(self, page: int) -> tuple[int, bool]:
        """Copy-on-write claim: exclusive owner keeps the page (False);
        a shared page is swapped for a fresh allocation (True — the
        caller must copy the payload) and the share is released. Refs
        balance by construction: +1 alloc, -1 unref."""
        if self._refs[page] <= 0:
            raise ValueError(f"cow of free page {page}")
        if self._refs[page] == 1:
            return page, False
        fresh = self.alloc()
        self.unref(page)
        return fresh, True

    def refcount(self, page: int) -> int:
        return self._refs[page]

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def shared_count(self) -> int:
        """Pages referenced more than once (the dedup win the gauges show)."""
        return sum(1 for r in self._refs if r > 1)


@dataclasses.dataclass
class _Node:
    """One page-sized chunk of a cached prefix. The trie holds exactly one
    pool reference per node (dropped on eviction)."""
    chunk: tuple
    page: int
    parent: Optional["_Node"]
    children: dict = dataclasses.field(default_factory=dict)
    pinned: bool = False      # on a register_prefix path: never evicted
    last_used: int = 0


@dataclasses.dataclass
class MatchResult:
    pages: list          # matched page ids, in prompt order, ONE REF HELD EACH
    matched_tokens: int  # pages * page_tokens


class PrefixTrie:
    """Radix trie over page-sized token chunks; one root per adapter id.
    Not thread-safe — the engine serializes under its prefix lock."""

    def __init__(self, pool: PagePool, page_tokens: int):
        if page_tokens < 1:
            raise ValueError(f"page_tokens must be >= 1, got {page_tokens}")
        self.pool = pool
        self.page_tokens = page_tokens
        self._roots: dict[int, dict] = {}
        # flat registry for LRU scans, keyed by id() so eviction and
        # adapter teardown remove in O(1) (a list's remove() would make
        # drop_adapter O(N^2) under the engine's prefix lock)
        self._nodes: dict[int, _Node] = {}
        self._clock = 0

    def __len__(self) -> int:
        return len(self._nodes)

    def _chunks(self, tokens: list, n: int):
        t = self.page_tokens
        return [tuple(tokens[i * t:(i + 1) * t]) for i in range(n)]

    def match(self, adapter_id: int, tokens: list) -> MatchResult:
        """Longest full-page prefix of ``tokens`` present in the trie,
        capped so AT LEAST ONE prompt token remains to compute (the
        engine needs last-position logits, so a fully-cached prompt still
        recomputes its final token — vLLM does the same). Every returned
        page carries one extra pool reference; the caller MUST
        ``release`` after gathering."""
        max_chunks = max(0, (len(tokens) - 1) // self.page_tokens)
        return self._match_chunks(adapter_id, tokens, max_chunks)

    def match_full(self, adapter_id: int, tokens: list) -> MatchResult:
        """Like ``match`` but WITHOUT the one-token-remaining cap: every
        full page of ``tokens`` that the trie holds, reference held. The
        paged decode loop builds a slot's page table from this — the slot
        references shared prefix pages read-only, so the final-token cap
        (a prefill/logits concern) does not apply."""
        return self._match_chunks(adapter_id, tokens,
                                  len(tokens) // self.page_tokens)

    def _match_chunks(self, adapter_id: int, tokens: list,
                      max_chunks: int) -> MatchResult:
        self._clock += 1
        node_map = self._roots.get(adapter_id, {})
        pages: list[int] = []
        for chunk in self._chunks(tokens, max_chunks):
            node = node_map.get(chunk)
            if node is None:
                break
            node.last_used = self._clock
            self.pool.ref(node.page)
            pages.append(node.page)
            node_map = node.children
        return MatchResult(pages, len(pages) * self.page_tokens)

    def release(self, pages: list) -> None:
        for p in pages:
            self.pool.unref(p)

    def insert(self, adapter_id: int, tokens: list,
               write_pages: Callable[[list, int], None],
               pin: bool = False) -> tuple[int, int]:
        """Cache every full page of ``tokens`` not already present.
        ``write_pages(page_ids, start_chunk)`` copies the KV payload into
        the arena BEFORE the nodes become matchable (same lock, so no
        reader can race it). Evicts LRU leaves when the pool runs dry —
        never a node on the path being extended. Returns (pages added,
        pages evicted)."""
        self._clock += 1
        want = len(tokens) // self.page_tokens
        node_map = self._roots.setdefault(adapter_id, {})
        parent: Optional[_Node] = None
        chunks = self._chunks(tokens, want)
        depth = 0
        path: list[_Node] = []
        for chunk in chunks:
            node = node_map.get(chunk)
            if node is None:
                break
            node.last_used = self._clock
            if pin:
                node.pinned = True
            parent, node_map, depth = node, node.children, depth + 1
            path.append(node)
        evicted = 0
        new_nodes: list[_Node] = []
        protect = set(id(n) for n in path)
        for chunk in chunks[depth:]:
            try:
                page = self.pool.alloc()
            except PoolExhausted:
                evicted += self._evict_lru(protect)
                try:
                    page = self.pool.alloc()
                except PoolExhausted:
                    break  # nothing evictable: cache what we could
            node = _Node(chunk=chunk, page=page, parent=parent, pinned=pin,
                         last_used=self._clock)
            new_nodes.append(node)
            protect.add(id(node))
            parent = node
        if new_nodes:
            # payload first, visibility second (one lock, but the order
            # keeps a future finer-locking refactor honest)
            write_pages([n.page for n in new_nodes], depth)
            node_map = (self._roots[adapter_id] if not path
                        else path[-1].children)
            for node in new_nodes:
                node_map[node.chunk] = node
                self._nodes[id(node)] = node
                node_map = node.children
        return len(new_nodes), evicted

    def insert_ready(self, adapter_id: int, tokens: list, pages: list,
                     pin: bool = False) -> int:
        """Cache full pages of ``tokens`` whose KV payload ALREADY sits
        in the arena pages the caller owns (paged-native prefill
        scattered the run in place — there is nothing to copy, the
        zero-copy sibling of ``insert``). ``pages[i]`` backs chunk i;
        each adopted node takes its OWN pool reference, so the caller's
        run references stay the caller's to release. Chunks already
        present dedup through the walk (the caller's duplicate page is
        simply not adopted — the slot keeps decoding from its own run).
        Returns pages adopted."""
        self._clock += 1
        want = min(len(pages), len(tokens) // self.page_tokens)
        node_map = self._roots.setdefault(adapter_id, {})
        parent: Optional[_Node] = None
        depth = 0
        chunks = self._chunks(tokens, want)
        for chunk in chunks:
            node = node_map.get(chunk)
            if node is None:
                break
            node.last_used = self._clock
            if pin:
                node.pinned = True
            parent, node_map, depth = node, node.children, depth + 1
        added = 0
        for i, chunk in enumerate(chunks[depth:]):
            page = pages[depth + i]
            self.pool.ref(page)
            node = _Node(chunk=chunk, page=page, parent=parent, pinned=pin,
                         last_used=self._clock)
            node_map[chunk] = node
            self._nodes[id(node)] = node
            parent, node_map = node, node.children
            added += 1
        return added

    def _evict_lru(self, protect: set) -> int:
        """Drop the least-recently-used unpinned LEAF (children would
        orphan otherwise; parents become leaves as their subtrees drain).
        The pool frees the page only if no in-flight match still holds it
        — eviction never frees a referenced page. Returns 1/0."""
        victim: Optional[_Node] = None
        for node in self._nodes.values():
            if node.children or node.pinned or id(node) in protect:
                continue
            if victim is None or node.last_used < victim.last_used:
                victim = node
        if victim is None:
            return 0
        owner = (victim.parent.children if victim.parent is not None
                 else self._roots_containing(victim))
        owner.pop(victim.chunk, None)
        del self._nodes[id(victim)]
        self.pool.unref(victim.page)
        return 1

    def _roots_containing(self, node: _Node) -> dict:
        for root in self._roots.values():
            if root.get(node.chunk) is node:
                return root
        return {}

    def drop_adapter(self, adapter_id: int) -> int:
        """Forget an adapter's whole subtree (its weights were replaced,
        so its cached prefix KV is stale). Returns pages released."""
        root = self._roots.pop(adapter_id, None)
        if root is None:
            return 0
        dropped = 0
        stack = list(root.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            del self._nodes[id(node)]
            self.pool.unref(node.page)
            dropped += 1
        return dropped

    def shared_pages(self) -> int:
        """Pages whose KV serves more than one cached sequence: an interior
        node's page backs its own path AND every extension under it, and a
        refcount > 1 means an in-flight gather also holds it."""
        return sum(1 for n in self._nodes.values()
                   if n.children or self.pool.refcount(n.page) > 1)

    def stats(self) -> dict:
        return {"nodes": len(self._nodes),
                "pinned": sum(1 for n in self._nodes.values()
                              if n.pinned),
                "adapters": sorted(self._roots)}


class DensePrefixStore:
    """Registered-prefix fallback for ring/mixed (``abs_pos``) cache
    layouts, which cannot page: position p lives at ring slot p %% R and
    early positions are overwritten by design, so the only faithful
    snapshot is the whole single-slot cache at prefix end — exactly what
    the pre-paged registry stored. Same semantics as before: longest
    registered prefix wins, per-adapter variants fill lazily (adapter
    deltas flow into K/V) and are LRU-bounded by ``max_adapter_variants``
    while base variants stay pinned. Not thread-safe (engine lock)."""

    @dataclasses.dataclass
    class _Entry:
        tokens: list
        variants: dict
        lru: dict = dataclasses.field(default_factory=dict)

    def __init__(self, max_adapter_variants: int):
        self.max_adapter_variants = max_adapter_variants
        self._entries: list[DensePrefixStore._Entry] = []
        self._clock = 0

    def __len__(self) -> int:
        return len(self._entries)

    def lookup(self, tokens: list):
        """Longest registered prefix of ``tokens`` (entries are kept
        longest-first), or None."""
        return next((e for e in self._entries
                     if len(e.tokens) <= len(tokens)
                     and tokens[:len(e.tokens)] == e.tokens), None)

    def has(self, tokens: list) -> bool:
        return any(e.tokens == tokens for e in self._entries)

    def add(self, tokens: list, base_variant) -> None:
        self._entries.append(self._Entry(tokens=list(tokens),
                                         variants={0: base_variant}))
        self._entries.sort(key=lambda e: -len(e.tokens))  # longest first

    def touch(self, entry, adapter_id: int) -> None:
        self._clock += 1
        entry.lru[adapter_id] = self._clock

    def put_variant(self, entry, adapter_id: int, var) -> bool:
        """Store a lazily-built adapter variant (False if a racing fill
        won); evicts LRU adapter variants past the budget — base
        variants were explicitly registered and stay pinned."""
        if adapter_id in entry.variants:
            return False
        entry.variants[adapter_id] = var
        self.touch(entry, adapter_id)
        cap = self.max_adapter_variants
        while True:
            ad_vars = [(e.lru.get(aid, 0), e, aid)
                       for e in self._entries
                       for aid in e.variants if aid != 0]
            if len(ad_vars) <= cap:
                return True
            _, victim, aid = min(ad_vars, key=lambda t: t[0])
            del victim.variants[aid]
            victim.lru.pop(aid, None)

    def drop_adapter(self, adapter_id: int) -> None:
        for e in self._entries:
            e.variants.pop(adapter_id, None)
            e.lru.pop(adapter_id, None)

    def snapshot(self) -> list:
        return [{"tokens": len(e.tokens),
                 "adapter_variants": len(e.variants)}
                for e in self._entries]


# -- device arena -------------------------------------------------------------
# jax imports stay inside the builders: PagePool/PrefixTrie/DensePrefixStore
# are jax-free, so the tier-1 unit tests run host-only.

def _build_gather(t: int, out_shardings=None):
    """One jit per POWER-OF-TWO page count: callers pad ``ids`` up to a
    bucket (repeating a valid page id) and pass the true token count as
    ``index_val`` — padded positions land beyond ``index``, which the
    attention mask never exposes and later writes overwrite (the same
    decode-path invariant padded prefill relies on). Bounds compile
    variants to log2(cache_len / page_tokens) instead of one per distinct
    prefix length.

    ``out_shardings`` (mesh serving): pin the produced single cache to
    the engine's construction shardings — left to GSPMD, each arena jit
    would pick (and normalize) its own layout, the arrays' sharding keys
    would flap between producers, and every consumer jit (the paged
    decode step above all) would recompile per producer. One pinned
    form everywhere = one executable everywhere."""
    import jax
    import jax.numpy as jnp

    def gather(single, arena, ids, index_val):
        n = ids.shape[0]
        out = dict(single)
        for name, a in arena.items():
            frag = a[:, ids]  # (l, n, T, ...)
            frag = frag.reshape((a.shape[0], 1, n * t) + a.shape[3:])
            out[name] = single[name].at[:, :, :n * t].set(frag)
        out["index"] = jnp.broadcast_to(
            index_val.astype(jnp.int32), (1,))
        return out

    if out_shardings is None:
        return jax.jit(gather, donate_argnums=(0,))
    return jax.jit(gather, donate_argnums=(0,), out_shardings=out_shardings)


def _build_write(t: int, out_shardings=None):
    """One jit per POWER-OF-TWO page count (callers binary-decompose a
    run of new pages); the token offset is a TRACED dynamic-slice start,
    so it never forces a recompile. ``out_shardings`` pins the arena's
    layout under mesh serving (see _build_gather)."""
    import jax

    def write(arena, single, ids, start_tok):
        n = ids.shape[0]
        out = {}
        for name, a in arena.items():
            frag = jax.lax.dynamic_slice_in_dim(single[name], start_tok,
                                                n * t, axis=2)
            frag = frag.reshape((a.shape[0], n, t) + a.shape[3:])
            out[name] = a.at[:, ids].set(frag)
        return out

    if out_shardings is None:
        return jax.jit(write, donate_argnums=(0,))
    return jax.jit(write, donate_argnums=(0,), out_shardings=out_shardings)


def _build_export(mesh=None):
    """One jitted gather over ALL sections for the streaming export path:
    a per-chunk flush calling eager per-section gathers would pay ~ms of
    dispatch per section per chunk — at streaming granularity that
    overhead would eat the very overlap the stream exists to create.
    Callers pad ``ids`` to a power-of-two bucket (compile O(log)
    variants) and slice the padding off after their host copy.

    Mesh serving (ISSUE 12): the export is jitted with REPLICATED
    out_shardings, so a sharded arena's run leaves as a host-replicated
    fragment — the wire codec, the stream assembler and np.asarray on a
    handler thread all see exactly the single-device layout (one gather
    here instead of one per consumer); device-path adoption re-shards on
    insert, where the write jit owns the layout anyway."""
    import jax

    def export(arena, ids):
        return {name: a[:, ids] for name, a in arena.items()}

    if mesh is None:
        return jax.jit(export)
    from jax.sharding import NamedSharding, PartitionSpec
    return jax.jit(export,
                   out_shardings=NamedSharding(mesh, PartitionSpec()))


def _build_fill(t: int, out_shardings=None):
    """``_build_write`` with a T-token pad on the source: a slot's tail
    fill copies ceil(remaining / T) pages from a single-request cache, and
    the last page's slice may reach up to T-1 positions past the cache's
    length — dynamic_slice would CLAMP the start and silently misalign
    the data. The pad makes the overshoot read zeros instead (positions
    beyond the slot's length: masked by attention, overwritten by decode
    writes — the standard decode-path invariant). ``out_shardings`` pins
    the arena's layout under mesh serving (see _build_gather)."""
    import jax
    import jax.numpy as jnp

    def fill(arena, single, ids, start_tok):
        n = ids.shape[0]
        out = {}
        for name, a in arena.items():
            src = jnp.pad(single[name],
                          [(0, 0), (0, 0), (0, t)]
                          + [(0, 0)] * (single[name].ndim - 3))
            frag = jax.lax.dynamic_slice_in_dim(src, start_tok, n * t,
                                                axis=2)
            frag = frag.reshape((a.shape[0], n, t) + a.shape[3:])
            out[name] = a.at[:, ids].set(frag)
        return out

    if out_shardings is None:
        return jax.jit(fill, donate_argnums=(0,))
    return jax.jit(fill, donate_argnums=(0,), out_shardings=out_shardings)


class PagedKVStore:
    """The HBM arena behind PagePool/PrefixTrie: one array per KV cache
    section, section shape with batch -> n_pages and positions ->
    page_tokens (rank preserved, so ``kv_cache_pspec`` applies verbatim
    and the kv-heads axis stays tensor-sharded under mesh serving).

    Generic over the section dict, so plain K/V, int8 K/V (+ scales) and
    MLA latent caches all page; ring/mixed layouts are the caller-gated
    exception (DensePrefixStore). All methods — including every arena
    read — must run under the engine's prefix lock: ``write`` donates the
    arena, and a gather racing a donation would read freed buffers."""

    def __init__(self, n_pages: int, page_tokens: int,
                 single_shape_fn: Callable, mesh=None,
                 arena_sharding: str = "auto"):
        """``mesh``: allocate the arena DIRECTLY under its NamedSharding
        (ISSUE 12: a TP engine's paged hot path serves from a sharded
        arena — constructing replicated and resharding after would
        transiently double HBM at exactly the scale sharding exists
        for). ``arena_sharding``: "auto" shards each section per
        kv_cache_pspec (kv-heads over ``tensor``; MLA latents replicate
        — they have no head axis); "replicate" pins every section
        replicated — the fallback for head counts the mesh doesn't
        divide (pays memory, keeps paged decode)."""
        import jax
        import jax.numpy as jnp

        if arena_sharding not in ("auto", "replicate"):
            raise ValueError(f"arena_sharding must be 'auto' or "
                             f"'replicate', got {arena_sharding!r}")
        self.page_tokens = page_tokens
        self.arena_sharding = arena_sharding
        self.pool = PagePool(n_pages)
        self.trie = PrefixTrie(self.pool, page_tokens)
        shapes = jax.eval_shape(single_shape_fn)
        sections = {name: sd for name, sd in shapes.items()
                    if name != "index"}
        if any(name == "abs_pos" for name in sections):
            raise ValueError("ring/mixed (abs_pos) caches cannot page; "
                             "gate on the engine's ring_len")

        def build() -> dict:
            return {name: jnp.zeros(
                (sd.shape[0], n_pages, page_tokens) + sd.shape[3:], sd.dtype)
                for name, sd in sections.items()}

        arena_sh = single_sh = self._replicated = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            ashapes = jax.eval_shape(build)
            arena_sh = {
                name: NamedSharding(
                    mesh,
                    PartitionSpec() if arena_sharding == "replicate"
                    else kv_cache_pspec(name, sd.ndim))
                for name, sd in ashapes.items()}
            # the single-request caches the gather produces follow the
            # SAME construction shardings the engine's _fresh_cache uses
            # (kv_cache_pspec) — equal sharding objects everywhere keep
            # every consumer jit at one executable
            single_sh = {name: NamedSharding(mesh,
                                             kv_cache_pspec(name, sd.ndim))
                         for name, sd in shapes.items()}
            # replicated-export target for the eager per-section path
            # (export_pages); the jitted all-section path bakes it into
            # _build_export's out_shardings
            self._replicated = NamedSharding(mesh, PartitionSpec())
        if mesh is None:
            self.arena = build()
        else:
            self.arena = jax.jit(build, out_shardings=arena_sh)()
        self._gather = _build_gather(page_tokens, out_shardings=single_sh)
        self._write = _build_write(page_tokens, out_shardings=arena_sh)
        self._fill = _build_fill(page_tokens, out_shardings=arena_sh)
        self._export = _build_export(mesh)

    @property
    def page_bytes(self) -> int:
        """HBM bytes one page pins across all sections (K+V+scales, all
        layers) — the bench/telemetry sizing number."""
        return sum(int(a.dtype.itemsize)
                   * int(a.size) // a.shape[1]
                   for a in self.arena.values())

    def match(self, adapter_id: int, tokens: list) -> MatchResult:
        return self.trie.match(adapter_id, tokens)

    def match_full(self, adapter_id: int, tokens: list) -> MatchResult:
        """Every cached full page of ``tokens``, no final-token cap —
        the paged decode loop's slot-table source (see PrefixTrie)."""
        return self.trie.match_full(adapter_id, tokens)

    def alloc_run(self, n: int) -> list[int]:
        """``n`` private pages (refcount 1 each), evicting LRU trie
        leaves as needed. All-or-nothing: on exhaustion the partial run
        is released and PoolExhausted raised — a slot with half its
        positions backed would decode garbage."""
        pages: list[int] = []
        try:
            for _ in range(n):
                try:
                    pages.append(self.pool.alloc())
                except PoolExhausted:
                    if not self.trie._evict_lru(set()):
                        raise
                    pages.append(self.pool.alloc())
        except PoolExhausted:
            for p in pages:
                self.pool.unref(p)
            raise
        return pages

    def fill_pages(self, single: dict, pages: list, start_tok: int) -> None:
        """Copy positions ``start_tok ..`` of a single-request cache into
        ``pages`` (binary decomposition over pow2 jit buckets, padded
        source so the last page's overshoot cannot misalign — see
        _build_fill). The caller owns the pages' references."""
        import jax.numpy as jnp
        off = 0
        while off < len(pages):
            size = 1 << ((len(pages) - off).bit_length() - 1)
            self.arena = self._fill(
                self.arena, single,
                jnp.asarray(pages[off:off + size], jnp.int32),
                jnp.asarray(start_tok + off * self.page_tokens, jnp.int32))
            off += size

    def section_spec(self) -> dict:
        """{name: (dtype name, per-page trailing shape)} — what a handoff
        blob must match to adopt into this arena (fleet/handoff.py's
        ``expect_sections``)."""
        return {name: (str(a.dtype), tuple(int(s) for s in a.shape[3:]))
                for name, a in self.arena.items()}

    def export_pages(self, pages: list) -> dict:
        """Device-side copies of the run's payload, {name: (L, n, T, ...)}.
        Returns fresh device arrays (the arena is read, never donated):
        the caller may np.asarray them OUTSIDE the engine's prefix lock —
        the copies stay valid across later arena donations. The caller
        holds the pages' references while this dispatches. Mesh serving:
        the copies come back HOST-REPLICATED (one gather at the source)
        so the wire codec and the device-handoff validators see the
        single-device layout; adoption re-shards on insert."""
        import jax
        import jax.numpy as jnp
        ids = jnp.asarray(pages, jnp.int32)
        out = {name: a[:, ids] for name, a in self.arena.items()}
        if self._replicated is not None:
            out = {name: jax.device_put(a, self._replicated)
                   for name, a in out.items()}
        return out

    def export_run(self, pages: list) -> dict:
        """``export_pages`` for the STREAMING path: one jitted dispatch
        over all sections per call (a per-chunk flush cannot afford eager
        per-section gathers), page list padded to a pow2 compile bucket
        by repeating the first id. Returns PADDED fresh device arrays —
        callers slice ``[:, :n]`` after their host copy (numpy slicing is
        free; a device-side trim would be one more dispatch). Same
        lifetime contract as export_pages."""
        import jax.numpy as jnp
        bucket = 1 << max(0, (len(pages) - 1).bit_length())
        padded = list(pages) + [pages[0]] * (bucket - len(pages))
        return self._export(self.arena, jnp.asarray(padded, jnp.int32))

    def adopt(self, adapter_id: int, tokens: list, sections: dict
              ) -> tuple[int, int]:
        """Insert a deserialized handoff run into the trie/arena.
        ``sections[name]`` is (L, n, T, ...) host or device data for the
        run's pages, in prompt order; ``tokens`` the n*T token ids they
        hold. Pages already cached dedup through the trie walk; only the
        missing suffix allocates. Returns (pages added, pages evicted)."""
        import jax.numpy as jnp
        n = len(tokens) // self.page_tokens
        # pad the position axis to a pow2 page count so the write jits
        # compile O(log) source variants, not one per adopted run length
        cap = 1 << max(0, (n - 1).bit_length())
        single_like = {}
        for name, arr in sections.items():
            a = jnp.asarray(arr)
            a = a.reshape((a.shape[0], 1, n * self.page_tokens)
                          + a.shape[3:])
            if cap > n:
                a = jnp.pad(a, [(0, 0), (0, 0),
                                (0, (cap - n) * self.page_tokens)]
                            + [(0, 0)] * (a.ndim - 3))
            single_like[name] = a
        return self.insert(adapter_id, list(tokens), single_like)

    def gather(self, pages: list, fresh_single: dict) -> dict:
        """Matched pages -> a single-request cache with positions
        0..matched filled and ``index`` set; ``fresh_single`` is donated.
        Caller still owns the match references (release after). The page
        list is padded to a power-of-two bucket (see _build_gather) so
        gathers compile O(log) variants, not one per prefix length."""
        import jax.numpy as jnp
        matched = len(pages) * self.page_tokens
        # position capacity of the single cache, from any paged section
        cap = next(s.shape[2] for n, s in fresh_single.items()
                   if n != "index") // self.page_tokens
        bucket = min(1 << (len(pages) - 1).bit_length(), cap)
        padded = list(pages) + [pages[0]] * (bucket - len(pages))
        return self._gather(fresh_single, self.arena,
                            jnp.asarray(padded, jnp.int32),
                            jnp.asarray(matched, jnp.int32))

    def release(self, pages: list) -> None:
        self.trie.release(pages)

    def insert(self, adapter_id: int, tokens: list, single: dict,
               pin: bool = False) -> tuple[int, int]:
        """Cache ``tokens``' full pages from a prefilled single-request
        cache (KV for positions 0..len(tokens) present). Returns
        (pages added, pages evicted)."""
        import jax.numpy as jnp

        def write_pages(page_ids: list, start_chunk: int):
            # binary decomposition: at most log2(run) jitted writes, each
            # compiled once per power-of-two size (see _build_write)
            off = 0
            while off < len(page_ids):
                size = 1 << ((len(page_ids) - off).bit_length() - 1)
                self.arena = self._write(
                    self.arena, single,
                    jnp.asarray(page_ids[off:off + size], jnp.int32),
                    jnp.asarray((start_chunk + off) * self.page_tokens,
                                jnp.int32))
                off += size

        return self.trie.insert(adapter_id, tokens, write_pages, pin=pin)

    def insert_ready(self, adapter_id: int, tokens: list, pages: list,
                     pin: bool = False) -> int:
        """Adopt a paged-native prefill's run into the trie WITHOUT a
        copy: the run's pages already hold the KV payload (the chunk
        steps scattered straight into the arena), so the trie only takes
        references (PrefixTrie.insert_ready). The caller keeps its own
        run references and releases them when the slot completes."""
        return self.trie.insert_ready(adapter_id, tokens, pages, pin=pin)

    def stats(self) -> dict:
        # evictable = unpinned trie pages ONLY the trie references
        # (refcount 1): evicting the node returns the page to the free
        # list NOW. A slot-referenced shared page is NOT reclaimable
        # until the slot completes — counting it would overstate the
        # decode pool's headroom and mute the page-exhaustion signal.
        evictable = sum(
            1 for node in self.trie._nodes.values()
            if not node.pinned and self.pool.refcount(node.page) == 1)
        return {"pages_total": self.pool.n_pages,
                "pages_free": self.pool.free_count,
                "pages_shared": self.trie.shared_pages(),
                "pages_evictable": evictable,
                **self.trie.stats()}
