"""Serving request/slot state and admission types.

The queueing DATA for the engine: ServingConfig (the knob surface),
Request (everything a submit carries through the prefill and decode
threads), _Slot (per-decode-slot host state), and the typed admission
rejections the HTTP layer maps to 429/503. The engine (engine.py) owns
the threads and locks; this module owns the shapes they exchange, so the
paged-KV manager and the sampler can be tested against plain dataclasses
without spinning an engine."""

from __future__ import annotations

import dataclasses
import threading
from concurrent.futures import Future
from typing import Any, Optional

# SLO histograms live sub-second: the default bucket ladder (0.5s first
# bucket, sized for pod provisioning) would crush every TTFT/ITL sample
# into one bin (ISSUE 2 satellite)
TTFT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                10.0, 30.0, 60.0)
ITL_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
               1.0, 2.5)
UTIL_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


@dataclasses.dataclass
class ServingConfig:
    slots: int = 4               # concurrent decode streams
    max_prefill_len: int = 512
    cache_len: int = 1024        # per-slot KV budget (prompt + generation)
    max_new_tokens: int = 128
    eos_token: int = -1          # -1 = never stop on a token
    temperature: float = 0.0     # 0 = greedy
    quantize_int8: bool = False  # weight-only int8 (models/quant.py): halves
                                 # weight HBM traffic on the bandwidth-bound
                                 # decode step
    # weight-only int4 (two weights per byte, group-wise scales): quarter
    # weight HBM traffic — the next rung after int8 on the decode-bandwidth
    # ladder. Covers MoE EXPERT weights too (per-expert unpack kernel,
    # tests pin parity vs f32 within a threshold). Accuracy drops more
    # than int8's (4-bit resolution); the tiny pinned model stays
    # argmax-stable in tests, real models deserve an eval before
    # production. Mutually exclusive with quantize_int8.
    quantize_int4: bool = False
    # speculative decoding via prompt-lookup (n-gram) proposals: draft this
    # many tokens per decode step and verify them in ONE forward pass
    # (models/llama.py verify_step). Greedy slots commit every matched draft
    # token "for free" (decode is memory-bound, so a K-token verify costs
    # about one decode step); sampled slots fall back to 1 token/step.
    # Greedy output equals the non-speculative engine's on the pinned f32
    # test model; the K-wide and 1-wide kernels can reduce in different
    # orders, so logits within ~1 ulp of a tie may tie-break differently
    # (bf16 especially) — same model quality, not a correctness loss.
    speculate_k: int = 0
    # Ring KV cache for uniformly-windowed models (Mistral): physical cache
    # per slot shrinks to ~window + write slack while cache_len stays the
    # LOGICAL budget (prompt + generation length cap). None = auto: on
    # whenever the model has a uniform sliding window and the ring is
    # actually smaller; True forces it (error if the model can't); False
    # disables.
    ring_cache: Optional[bool] = None
    # int8 KV cache with per-(position, kv-head) scales: decode reads the
    # whole cache every step (HBM-bound), so int8 halves that traffic and
    # doubles how many slots fit a chip. Composes with ring_cache and
    # quantize_int8 (weights). Accuracy: ~1e-2-level logit perturbation —
    # greedy outputs typically identical, pinned by tests on the tiny model.
    quantize_kv_int8: bool = False
    # donate the engine cache through decode/verify (in-place K-token
    # updates instead of a full-cache copy per step). The off-switch exists
    # to MEASURE that HBM claim (bench.py --econ); leave on in production.
    donate_cache: bool = True
    # registered-prefix cap: how many DISTINCT prefixes register_prefix()
    # will pin (as never-evicted trie nodes in the paged pool, or — on
    # ring/mixed cache layouts that cannot page — as dense single-slot
    # cache copies)
    max_prefixes: int = 8
    # -- paged KV prefix pool (ISSUE 8) ----------------------------------
    # cross-request prefix cache: every prompt is matched against a radix
    # trie of KV pages; matched full pages are GATHERED from the shared
    # HBM arena instead of re-prefilled, and every prefill's full pages
    # are inserted back (refcounted, LRU-leaf eviction). Off = the trie
    # and arena are never allocated; register_prefix still works on
    # ring/mixed layouts via the dense fallback.
    prefix_cache_enabled: bool = True
    # tokens per KV page (the pool's allocation and trie-match granule).
    # Prefixes shorter than one page gain nothing; 16 matches vLLM's
    # default block and divides every power-of-two prefill bucket.
    kv_page_tokens: int = 16
    # pages in the preallocated arena. 0 = auto: one decode-cache's worth
    # (slots * cache_len / kv_page_tokens), so the prefix pool can at most
    # double KV HBM and is usually far under it. With the paged decode
    # loop on, auto doubles (decode slots live IN the arena, so it must
    # hold the slots' residency plus the shared prefix pool).
    kv_pool_pages: int = 0
    # -- TP paged serving (ISSUE 12) -------------------------------------
    # how the arena sections place over a serving mesh. "auto": K/V (and
    # scale) sections shard their kv-heads axis over ``tensor`` exactly
    # like the contiguous cache (kv_cache_pspec; MLA latent sections
    # replicate — headless), degrading to a fully replicated arena when
    # the mesh doesn't divide the kv-head count. "replicate" pins the
    # replicated layout (every shard holds the whole arena — pays HBM,
    # keeps paged decode; a debugging/odd-geometry escape hatch).
    # Ignored off-mesh.
    kv_arena_sharding: str = "auto"
    # -- paged decode loop (ISSUE 9) -------------------------------------
    # run the decode hot loop on per-slot page tables over the shared
    # arena (LlamaModel.paged_decode_step): prefix hits and handed-off KV
    # are REFERENCED zero-copy instead of gathered into a contiguous slot
    # cache, and each admission writes only its un-cached tail pages.
    # None = auto: on whenever the config allows it (prefix cache on,
    # kv_page_tokens < cache_len, not a contiguous ring cache, no
    # interleaved sliding-window pattern, pool sized for the fleet).
    # Every cache layout pages (plain/int8-KV/MLA/MLA+int8/uniform
    # window), mesh-sharded arenas page (ISSUE 13), and since ISSUE 14
    # adapters and speculation ride the paged loop too. True errors if
    # the config can't; False keeps the contiguous slot-cache loop.
    paged_decode: Optional[bool] = None
    # paged-NATIVE prefill (ISSUE 14): when the paged loop is on, prefill
    # chunks scatter K/V straight into the slot's pre-allocated arena
    # pages (LlamaModel.paged_prefill_chunk_step) — no dense scratch
    # cache, no fill_pages copy on the hot path. None = auto: on whenever
    # the paged loop runs; False keeps the dense-scratch prefill +
    # page-copy adoption path; True errors unless the paged loop is on.
    # Fanout admissions (one prefill seeding several slots) and pool
    # exhaustion fall back to the dense route per-request either way.
    paged_prefill: Optional[bool] = None
    # multi-LoRA serving (vLLM-style multi-tenant adapters): rank > 0
    # preallocates zero-filled adapter stacks of this rank over
    # ``lora_targets`` so adapters register WITHOUT recompiling the decode
    # jit (the adapter axis is fixed at max_adapters+1; slot 0 = all-zeros
    # = base model). Requests pick an adapter by name via submit(adapter=).
    lora_rank: int = 0
    lora_targets: tuple = ("wq", "wv")
    max_adapters: int = 8
    # admission control: reject new requests once this many are queued
    # (0 = unbounded). The queue depth GAUGE stays the HPA scale signal;
    # this is the ceiling that keeps latency bounded until the autoscaler
    # catches up — rejected submits resolve to EngineOverloaded, which the
    # HTTP layer maps to 429 + Retry-After.
    max_queue_depth: int = 0
    # -- chunked prefill (ISSUE 10) --------------------------------------
    # process prompts in chunks of this many tokens, YIELDING to the
    # engine's decode loop between chunks (ChunkArbiter below): a long
    # prompt's prefill interleaves with co-resident streams' decode steps
    # instead of monopolizing the device, bounding their inter-token
    # latency — and each completed chunk's full KV pages can stream to a
    # decode replica while the next chunk computes (the overlapped
    # handoff). 0 = off (monolithic prefill, chunked only at
    # max_prefill_len with no interleave — the pre-ISSUE-10 behavior).
    # Chunked output is token-identical to monolithic (pinned by tests);
    # the knob trades the prefilling request's own TTFT (one decode-step
    # wait per chunk) for everyone else's ITL.
    serving_chunk_tokens: int = 0
    # -- flight recorder (ISSUE 17) --------------------------------------
    # per-decode-step timeline: a bounded ring of step records (batch
    # composition, schedule/kernel/sample/commit phase split on the
    # engine's _perf clock, arena page counts, speculative accounting)
    # served at GET /debug/steps and folded into serving.request spans.
    # Off means the engine holds no recorder at all — the hot path pays
    # one `is not None` test per mark site and nothing else. The ring is
    # double-bounded: at most recorder_steps records AND at most
    # recorder_bytes of serialized payload (oldest evict first).
    flight_recorder: bool = True
    recorder_steps: int = 512
    recorder_bytes: int = 262144
    # -- cost attribution (ISSUE 20) -------------------------------------
    # per-request chip-second metering (workloads/serving/costmeter.py):
    # phase walls the engine already stamps (queue/prefill/decode) priced
    # through the generations.py table, KV page-seconds of arena occupancy,
    # per-tenant ledger, idle-burn gauge. Off = the engine holds no meter;
    # the hot path pays one `is not None` test per completion and nothing
    # else (the flight-recorder bargain).
    cost_meter: bool = True


class EngineOverloaded(RuntimeError):
    """Request rejected at admission: queue is at max_queue_depth."""


class EngineDraining(RuntimeError):
    """Request rejected at admission: the engine is draining (fleet
    scale-down). In-flight and already-queued requests still finish; the
    HTTP layer maps this to 503 + Retry-After so clients re-resolve to
    another replica."""


@dataclasses.dataclass
class Request:
    prompt: list[int]
    max_new_tokens: int
    rid: str
    future: Future
    submitted_at: float
    temperature: float
    top_k: int = 0          # 0 = no top-k filter
    top_p: float = 1.0      # 1.0 = no nucleus filter
    # OpenAI sampling penalties, applied to the logits BEFORE temperature/
    # filtering: presence subtracts once per token SAMPLED DURING
    # GENERATION (the prompt never contributes — OpenAI's published
    # formula and vLLM both count output tokens only), frequency per
    # occurrence. A penalized request never takes the speculative K-wide
    # greedy commit (each committed token changes the next step's
    # penalties).
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # OpenAI logit_bias: {token_id: bias in [-100, 100]} added to that
    # token's logit every step (-100 ~ ban, +100 ~ force)
    logit_bias: Optional[dict] = None
    adapter_id: int = 0     # multi-LoRA slot (0 = base model)
    # stop token SEQUENCES: generation ends when the generated tail equals
    # one (the matched sequence stays in the output; callers strip it).
    # Checked host-side per committed token — no jit impact.
    stop: list = dataclasses.field(default_factory=list)
    # stop STRINGS matched on DECODED text (needs the engine's decode_fn):
    # exact for BPE vocabularies where a stop string can straddle a token
    # boundary and the token-sequence fast path above would miss it.
    # Generation ends when the decoded output contains one; the matched
    # text stays in the output (callers truncate at its first occurrence).
    stop_texts: list = dataclasses.field(default_factory=list)
    # return per-token log P(token | prefix) of each generated token
    logprobs: bool = False
    # sampling seed (resolved at submit): the PRNG stream is a pure
    # function of (seed, draw index), independent of slot placement and
    # neighbors. On speculative engines bit-exactness additionally needs
    # the logits to be batch-independent — a bf16 near-tie can round
    # differently between the K-wide and 1-wide kernels (ServingConfig.
    # speculate_k caveat), so there "same seed = same distribution" is
    # the hard guarantee and exact tokens the overwhelmingly common case.
    seed: int = 0
    # streaming: called with each generated token id, from the engine thread.
    # A raising callback (client gone) cancels the request at the next token.
    on_token: Optional[Any] = None
    # co-submitted requests with the IDENTICAL prompt (OpenAI n>1): the
    # prefill runs ONCE and its immutable cache fans out to every member
    # (nothing donates the single cache, so sharing is safe); each member
    # samples its own first token from the shared last-position logits
    fanout: Optional[list] = None
    # distributed-tracing context (W3C traceparent): trace_id groups this
    # request's spans with the caller's trace; span_id is the REQUEST root
    # span's id (the HTTP layer generates it so it can stamp the response
    # header before the request finishes); parent_span_id is the caller's
    # inbound span. Empty = the engine mints ids at completion.
    trace_id: str = ""
    span_id: str = ""
    parent_span_id: str = ""
    # span-boundary timestamps (perf_counter domain, like submitted_at):
    # queue-wait = submitted->dequeued, prefill = dequeued->prefill_done,
    # decode = prefill_done->finish (contiguous: ready-queue wait and slot
    # insertion are decode-span preamble, so child durations sum to the
    # request latency)
    dequeued_at: float = 0.0
    prefill_done_at: float = 0.0
    first_token_at: float = 0.0
    # prefix-cache outcome, stamped by the prefill thread: how many prompt
    # tokens were served from shared KV pages instead of being prefilled
    # (0 = full prefill). Rides the serving.request span as
    # prefix_hit/matched_prefix_tokens attrs.
    matched_prefix_tokens: int = 0
    # cost-attribution tenant (ISSUE 20, the ROADMAP item-4 accounting
    # seam): optional X-Tenant header / OpenAI `user` field, threaded
    # router -> HTTP layer -> engine. Empty = unattributed ("-" in the
    # ledger). Purely an accounting label today; per-tenant QoS will hang
    # admission policy off the same field.
    tenant: str = ""


@dataclasses.dataclass
class _Slot:
    request: Optional[Request] = None
    generated: list[int] = dataclasses.field(default_factory=list)
    logprobs: list[float] = dataclasses.field(default_factory=list)
    remaining: int = 0
    last_token: int = 0
    # prompt-lookup drafting state: bigram -> latest start position over
    # prompt+generated, indexed lazily in _propose — amortized O(1)/token
    # where a rescan would be O(context) Python per engine step
    bigram_index: dict = dataclasses.field(default_factory=dict)
    indexed_upto: int = 0
    # stop_texts running tail: token ids whose decode is kept just long
    # enough (in CHARS) to contain any new stop-string match — trimming by
    # decoded length (not token count) survives zero-char specials and
    # detokenizer first-token artifacts (r3 advisor finding)
    stop_tail: list[int] = dataclasses.field(default_factory=list)
    stop_tail_upto: int = 0
    # inter-token-latency bookkeeping: perf_counter of the last token this
    # slot streamed (0 = none yet)
    last_emit_at: float = 0.0
    # paged decode loop (ISSUE 9): the slot's page-table row — page ids in
    # position order, ONE pool reference held per DISTINCT physical page
    # (shared prefix pages read-only, tail pages private); kv_len is the
    # committed token count = the next decode write position. Empty/0 on
    # the contiguous engine. table_len counts LOGICAL table entries
    # populated — it equals len(pages) on full-attention slots but runs
    # ahead of it on sliding-window slots, whose out-of-window physical
    # pages RECYCLE through later entries (ISSUE 11's paged ring run), so
    # one physical page may back several logical entries.
    pages: list[int] = dataclasses.field(default_factory=list)
    kv_len: int = 0
    table_len: int = 0


class ChunkArbiter:
    """Chunk-vs-decode arbitration for chunked prefill (ISSUE 10).

    The prefill thread calls ``yield_for_decode`` between chunk
    dispatches; when any decode slot is live it blocks until the engine
    thread reports one COMPLETED decode step (``decode_step_done`` after
    every ``_decode_once``), so the device order becomes chunk, decode
    step, chunk, ... instead of a monolithic prefill starving every
    co-resident stream. With no live slots the yield is free — an idle
    engine prefills at full speed.

    The timeout is a liveness backstop only (the last slot can complete
    between the check and the wait; the engine's crash path fails slots
    without a step): correctness never depends on it. Multiple prefill
    threads (register_prefix runs on handler threads) share one arbiter —
    notify_all wakes every waiter per step."""

    def __init__(self):
        self._cond = threading.Condition()
        self._steps = 0

    def decode_step_done(self) -> None:
        with self._cond:
            self._steps += 1
            self._cond.notify_all()

    def yield_for_decode(self, active_fn, timeout_s: float = 0.5) -> int:
        """Block until >= 1 decode step ran (returns how many), or return
        0 immediately when ``active_fn()`` says nothing is decoding. The
        timeout must comfortably exceed one decode step (it is a WEDGE
        backstop, not a pacing knob — timing out while a genuine step is
        mid-flight would let chunks queue ahead of it, re-creating the
        monopolization chunking exists to break)."""
        with self._cond:
            start = self._steps
            if not active_fn():
                return 0
            self._cond.wait_for(
                lambda: self._steps > start or not active_fn(),
                timeout=timeout_s)
            return self._steps - start


def _fail_future(fut: Future, exc: BaseException) -> None:
    """set_exception tolerant of a client cancel landing between a done()
    check and the call — InvalidStateError here must never kill an engine
    or prefill thread."""
    try:
        if not fut.done():
            fut.set_exception(exc)
    except Exception:  # noqa: BLE001 — racing future.cancel()
        pass
