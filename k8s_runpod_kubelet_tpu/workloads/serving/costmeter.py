"""Per-request chip-second metering and the replica cost ledger (ISSUE 20).

The fleet PRICES capacity (generations.py $/chip-hr drives the scheduler)
but until this module attributed none of it: no request, model, pool, or
tenant ever learned what it cost. CostMeter converts the timings the
engine already produces into chip-seconds and dollars:

- phase walls from the span-boundary timestamps every Request carries
  (queue = submitted->dequeued, prefill = dequeued->prefill_done,
  decode = prefill_done->end — contiguous by construction, so per-phase
  chip-seconds TELESCOPE to request wall x chips exactly);
- KV page-seconds of arena occupancy (trapezoid over the page count at
  prefill end and at completion — O(1) per request, no per-step sampling);
- dollars via the ONE generations.py price table (never a local copy —
  tests/test_generations.py AST-scans consumers for drifting literals).

Attribution is keyed (model, pool/generation, tenant); the tenant rides
a new optional ``X-Tenant`` header / OpenAI ``user`` field threaded
router -> engine (the ROADMAP item-4 accounting seam). Everything lands
three ways: ``serving.request`` span attrs, zero-seeded Prometheus
metrics, and a cumulative ledger snapshot that rides the fleet heartbeat
into ``/debug/costs``.

Deliberately stdlib-only and jax-free, like the recorder and tracer —
and like them it must never fail a request: the engine wraps every call
in try/except.
"""

from __future__ import annotations

import threading
import time

from ...generations import cost_per_chip_hr, generation_of

# /debug/costs JSON shape; tools/cost_summary.py warns on unknown versions
COSTS_SCHEMA_VERSION = 1

# $/request lives many decades below the provisioning-latency default
# ladder; sub-cent buckets keep single-request costs distinguishable
COST_BUCKETS = (0.000001, 0.00001, 0.0001, 0.001, 0.01, 0.1, 1.0, 10.0)

# per-tenant ledger cardinality bound: adversarial/typo'd tenant strings
# must not grow the snapshot without limit. Overflow tenants aggregate
# under one bucket (their spend still counts, just not separably).
MAX_TENANTS = 64
OVERFLOW_TENANT = "~other"

# the ledger key for requests that carried no tenant
NO_TENANT = "-"

PHASES = ("queue", "prefill", "decode")


def _zero_bucket() -> dict:
    return {"requests": 0, "tokens": 0, "prompt_tokens": 0,
            "chip_seconds": {p: 0.0 for p in PHASES},
            "kv_page_seconds": 0.0, "cost_dollars": 0.0}


def _fold(bucket: dict, attribution: dict) -> None:
    bucket["requests"] += 1
    bucket["tokens"] += attribution["tokens"]
    bucket["prompt_tokens"] += attribution["prompt_tokens"]
    for p in PHASES:
        bucket["chip_seconds"][p] += attribution["chip_seconds"][p]
    bucket["kv_page_seconds"] += attribution["kv_page_seconds"]
    bucket["cost_dollars"] += attribution["cost_dollars"]


class CostMeter:
    """One per engine. ``meter_request`` is the only hot-path entry point
    (one call per COMPLETED request — never per token or per step, so the
    attribution overhead rides far under the flight-recorder 2% bar)."""

    def __init__(self, metrics, *, model: str = "", accelerator: str = "",
                 chips: int = 1, pool: str = "", clock=time.monotonic):
        self.metrics = metrics
        self.model = model
        self.generation = generation_of(accelerator)
        self.pool = pool or self.generation
        self.chips = max(1, int(chips))
        self.price_per_chip_s = cost_per_chip_hr(self.generation) / 3600.0
        self._clock = clock
        self._started_at = clock()
        self._lock = threading.Lock()
        self._total = _zero_bucket()
        self._tenants: dict[str, dict] = {}
        self._handoff_bytes = 0
        self._describe(metrics)

    @staticmethod
    def _describe(m) -> None:
        """Catalog + zero-seed every meter metric up front (the repo's
        scrape-from-zero discipline; graftlint reads the literal names)."""
        m.describe("tpu_serving_request_cost_dollars",
                   "attributed $ per completed request (chip-seconds x "
                   "generations.py list price)", buckets=COST_BUCKETS)
        m.describe("tpu_serving_chip_seconds",
                   "attributed chip-seconds by request phase "
                   "(queue/prefill/decode; telescopes to wall x chips)")
        m.describe("tpu_serving_kv_page_seconds",
                   "KV arena occupancy attributed to requests, page-seconds")
        m.describe("tpu_serving_metered_requests",
                   "requests the cost meter attributed")
        m.describe("tpu_serving_idle_chip_seconds",
                   "paid chips x elapsed minus attributed chip-seconds "
                   "(the burn no request is paying for)")
        m.incr("tpu_serving_chip_seconds", 0, labels={"phase": "queue"})
        m.incr("tpu_serving_chip_seconds", 0, labels={"phase": "prefill"})
        m.incr("tpu_serving_chip_seconds", 0, labels={"phase": "decode"})
        m.incr("tpu_serving_kv_page_seconds", 0)
        m.incr("tpu_serving_metered_requests", 0)
        m.set_gauge("tpu_serving_idle_chip_seconds", 0.0)

    def meter_request(self, req, *, end_at: float, generated_tokens: int,
                      pages_end: int, page_tokens: int) -> dict:
        """Attribute one completed request. ``end_at`` is the engine's
        perf-clock completion stamp; ``pages_end`` is the slot's page count
        CAPTURED BEFORE release. Returns the attribution dict the caller
        folds into the serving.request span."""
        # clamp boundaries monotone so phases telescope exactly to
        # end - submitted even when a stamp was never set (failed prefill
        # leaves prefill_done_at = 0)
        b0 = req.submitted_at
        b1 = max(b0, req.dequeued_at or b0)
        b2 = max(b1, req.prefill_done_at or b1)
        b3 = max(b2, end_at)
        walls = {"queue": b1 - b0, "prefill": b2 - b1, "decode": b3 - b2}
        chip_seconds = {p: w * self.chips for p, w in walls.items()}
        page_tokens = max(1, int(page_tokens))
        pages_prefill = -(-len(req.prompt) // page_tokens)  # ceil div
        if pages_end <= 0:
            pages_end = pages_prefill
        kv_page_seconds = (pages_prefill * walls["prefill"]
                           + (pages_prefill + pages_end) / 2.0
                           * walls["decode"])
        cost = sum(chip_seconds.values()) * self.price_per_chip_s
        tenant = req.tenant or NO_TENANT
        attribution = {
            "tenant": tenant,
            "tokens": int(generated_tokens),
            "prompt_tokens": len(req.prompt),
            "chip_seconds": chip_seconds,
            "kv_page_seconds": kv_page_seconds,
            "cost_dollars": cost,
        }
        with self._lock:
            _fold(self._total, attribution)
            if tenant not in self._tenants and len(self._tenants) >= MAX_TENANTS:
                tenant = OVERFLOW_TENANT
            bucket = self._tenants.setdefault(tenant, _zero_bucket())
            _fold(bucket, attribution)
            idle = self._idle_locked()
        m = self.metrics
        m.observe("tpu_serving_request_cost_dollars", cost,
                  exemplar=req.trace_id or None)
        m.incr("tpu_serving_chip_seconds", chip_seconds["queue"],
               labels={"phase": "queue"})
        m.incr("tpu_serving_chip_seconds", chip_seconds["prefill"],
               labels={"phase": "prefill"})
        m.incr("tpu_serving_chip_seconds", chip_seconds["decode"],
               labels={"phase": "decode"})
        m.incr("tpu_serving_kv_page_seconds", kv_page_seconds)
        m.incr("tpu_serving_metered_requests")
        m.set_gauge("tpu_serving_idle_chip_seconds", idle)
        return attribution

    def note_handoff_bytes(self, nbytes: int) -> None:
        """KV handoff traffic attributed to this replica (cumulative)."""
        with self._lock:
            self._handoff_bytes += int(nbytes)

    def _idle_locked(self) -> float:
        paid = self.chips * max(0.0, self._clock() - self._started_at)
        attributed = sum(self._total["chip_seconds"].values())
        return max(0.0, paid - attributed)

    def span_attrs(self, attribution: dict) -> dict:
        """Flatten an attribution into serving.request span attrs."""
        cs = attribution["chip_seconds"]
        return {
            "cost_dollars": round(attribution["cost_dollars"], 9),
            "chip_seconds_queue": round(cs["queue"], 6),
            "chip_seconds_prefill": round(cs["prefill"], 6),
            "chip_seconds_decode": round(cs["decode"], 6),
            "kv_page_seconds": round(attribution["kv_page_seconds"], 6),
            "tenant": attribution["tenant"],
        }

    def snapshot(self) -> dict:
        """Cumulative replica ledger — rides every fleet heartbeat
        (idempotent, restart-guarded registry-side) and serves
        /debug/costs on the replica."""
        with self._lock:
            elapsed = max(0.0, self._clock() - self._started_at)
            return {
                "schema_version": COSTS_SCHEMA_VERSION,
                "model": self.model,
                "pool": self.pool,
                "generation": self.generation,
                "chips": self.chips,
                "price_per_chip_hr": round(self.price_per_chip_s * 3600.0, 6),
                "elapsed_s": round(elapsed, 3),
                "paid_chip_seconds": round(self.chips * elapsed, 3),
                "idle_chip_seconds": round(self._idle_locked(), 3),
                "handoff_bytes": self._handoff_bytes,
                "totals": _round_bucket(self._total),
                "tenants": {t: _round_bucket(b)
                            for t, b in sorted(self._tenants.items())},
            }


def _round_bucket(bucket: dict) -> dict:
    return {
        "requests": bucket["requests"],
        "tokens": bucket["tokens"],
        "prompt_tokens": bucket["prompt_tokens"],
        "chip_seconds": {p: round(v, 6)
                         for p, v in bucket["chip_seconds"].items()},
        "kv_page_seconds": round(bucket["kv_page_seconds"], 6),
        "cost_dollars": round(bucket["cost_dollars"], 9),
    }
