"""Serving engine package (split from the former 1.9k-line serving.py so
the paged KV pool, prefix trie, sampler, and queueing state land as
testable units):

- ``engine``     — ServingEngine: the threads, the decode loop, admission
- ``kv_manager`` — paged KV prefix pool: PagePool / PrefixTrie /
                   PagedKVStore / DensePrefixStore, kv_cache_pspec
- ``sampler``    — seeded per-request sampling, penalties, logit_bias
- ``scheduler``  — ServingConfig, Request, _Slot, admission exceptions

The public import surface is unchanged: everything previously importable
from ``workloads.serving`` re-exports here."""

from .engine import ServingEngine  # noqa: F401
from .kv_manager import (DensePrefixStore, MatchResult, PagedKVStore,  # noqa: F401
                         PagePool, PoolExhausted, PrefixTrie,
                         kv_cache_pspec)
from .sampler import _apply_penalties, _sample  # noqa: F401 — test seams
# (sampling / penalty formula unit tests import these directly)
from .scheduler import (EngineDraining, EngineOverloaded, Request,  # noqa: F401
                        ServingConfig, _fail_future, _Slot)

__all__ = [
    "ServingEngine", "ServingConfig", "Request", "_Slot",
    "EngineDraining", "EngineOverloaded",
    "PagePool", "PrefixTrie", "PagedKVStore", "DensePrefixStore",
    "MatchResult", "PoolExhausted", "kv_cache_pspec",
]
