"""Logical-axis sharding rules (GSPMD/MaxText style).

Model code annotates tensors with LOGICAL axis names ("batch", "embed", ...);
this module maps them to MESH axes per a rules table. Changing the parallelism
strategy = changing the rules, not the model. XLA inserts the collectives.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import AXES

# logical axis -> mesh axis (or tuple of mesh axes, or None = replicated).
# The default table implements: batch over (data, fsdp); params sharded over
# fsdp (ZeRO-3) and tensor; activations' sequence over seq (ring attention);
# heads/mlp over tensor; experts over expert.
LOGICAL_RULES: dict[str, object] = {
    # activation axes — batch soaks up both data-parallel axes; embed stays
    # unsharded on activations (a duplicate mesh axis in one spec is illegal,
    # and the fsdp all-gather happens on the PARAMS, not the activations)
    "batch": (AXES.DATA, AXES.FSDP),
    "seq": AXES.SEQ,               # context parallel (ring attention)
    "act_embed": None,
    "act_mlp": AXES.TENSOR,
    "act_heads": AXES.TENSOR,
    "act_vocab": AXES.TENSOR,
    # parameter axes — embed sharded over fsdp (ZeRO-3), output dims over tensor
    "embed": AXES.FSDP,
    "mlp": AXES.TENSOR,
    "heads": AXES.TENSOR,
    "kv_heads": AXES.TENSOR,
    "qkv": None,
    "head_dim": None,
    # MLA latent rank: replicated — every tensor shard's heads attend over
    # all positions' latents (models/llama.py param_logical_axes)
    "latent": None,
    # int4-packed weights: OUT axis over tensor, contraction replicated
    # (ops/int4_matmul.py int4_matmul_sharded shard_map layout contract).
    # Int4 EXPERT leaves do NOT use this rule: they shard their expert
    # axis only (quant.quantized_logical_axes bits=4 — out-sharding would
    # force an all-gather before the MoE combine under
    # moe._expert_ffn_sharded)
    "int4_out": AXES.TENSOR,
    "vocab": AXES.TENSOR,
    # MoE expert axis: expert weights' leading dim and the dispatch
    # buffer shard over it (EP serving composes with tensor on the mlp
    # axis; moe.py's shard_map island is the inference consumer)
    "expert": AXES.EXPERT,
    "stage": AXES.STAGE,
    "norm": None,
    # leading axis of scan-stacked layer params: sharded over the stage axis
    # so each pipeline stage's layers live on its devices (no-op at stage=1)
    "layer": AXES.STAGE,
}


def _mesh_axes_for(logical: Optional[str], rules: dict) -> object:
    if logical is None:
        return None
    return rules.get(logical)


def logical_spec(logical_axes: Sequence[Optional[str]],
                 rules: Optional[dict] = None) -> P:
    """('batch','seq','embed') -> PartitionSpec(('data','fsdp'),'seq','fsdp')."""
    rules = rules or LOGICAL_RULES
    return P(*[_mesh_axes_for(ax, rules) for ax in logical_axes])


def logical_sharding(mesh: Mesh, logical_axes: Sequence[Optional[str]],
                     rules: Optional[dict] = None) -> NamedSharding:
    return NamedSharding(mesh, logical_spec(logical_axes, rules))


def shard_logical(x, mesh: Mesh, logical_axes: Sequence[Optional[str]],
                  rules: Optional[dict] = None):
    """In-graph sharding constraint by logical axes (use inside jit)."""
    return jax.lax.with_sharding_constraint(
        x, logical_sharding(mesh, logical_axes, rules))


def param_shardings(mesh: Mesh, logical_tree, rules: Optional[dict] = None):
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings.
    ``logical_tree`` mirrors the param tree, leaves are tuples of logical
    axis names (as produced by models' ``logical_axes()`` helpers)."""
    return jax.tree_util.tree_map(
        lambda axes: logical_sharding(mesh, axes, rules),
        logical_tree,
        is_leaf=lambda v: isinstance(v, tuple),
    )
