"""Device-mesh parallelism for TPU workloads.

The workload-side half of SURVEY.md §2.4's parallelism table: every strategy the
kubelet's gang scheduling enables (dp/fsdp/tp/sp/pp/ep over ICI, multislice DCN)
is expressed here as mesh axes + sharding rules + jax.distributed bootstrap.
All communication is XLA collectives over the mesh — no NCCL/MPI analog exists
or is needed (SURVEY.md §5.8).

- ``mesh``:        MeshConfig -> jax.sharding.Mesh (ICI-aware axis ordering).
- ``sharding``:    logical-axis rules -> NamedSharding (MaxText-style).
- ``distributed``: jax.distributed init from the env the kubelet injects
                   (gang/env.py) — the two halves meet here.
"""

from .mesh import (AXES, MeshConfig, make_mesh, best_mesh_for, dp_width,
                   make_resized_mesh, resize_config)
from .sharding import (
    LOGICAL_RULES,
    logical_sharding,
    logical_spec,
    shard_logical,
    param_shardings,
)
from .distributed import (initialize_from_env, process_env_summary,
                          reinitialize_from_env, resize_env_summary,
                          surviving_process_env)
from .pipeline import pipeline_spmd, pipeline_stages

__all__ = [
    "AXES", "MeshConfig", "make_mesh", "best_mesh_for",
    "dp_width", "make_resized_mesh", "resize_config",
    "LOGICAL_RULES", "logical_sharding", "logical_spec", "shard_logical",
    "param_shardings",
    "initialize_from_env", "process_env_summary",
    "reinitialize_from_env", "resize_env_summary", "surviving_process_env",
    "pipeline_spmd", "pipeline_stages",
]
