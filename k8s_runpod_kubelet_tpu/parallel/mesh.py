"""Mesh construction: dp/fsdp/tp/sp/pp/ep axes over the device fabric.

Axis layout follows the scaling-book recipe: put the most communication-hungry
axes (tensor, sequence) innermost so their collectives ride ICI; data/fsdp
outermost so cross-slice (DCN) traffic is infrequent gradient reduction only.
``jax.experimental.mesh_utils.create_device_mesh`` handles the physical
topology mapping.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh


class AXES:
    """Canonical mesh axis names (order = outermost to innermost)."""

    DATA = "data"        # pure data parallel (replicated params)
    FSDP = "fsdp"        # data parallel with sharded params/optimizer (ZeRO-3)
    STAGE = "stage"      # pipeline parallel
    EXPERT = "expert"    # MoE expert parallel
    SEQ = "seq"          # sequence/context parallel (ring attention)
    TENSOR = "tensor"    # tensor (megatron-style) parallel

    ALL = (DATA, FSDP, STAGE, EXPERT, SEQ, TENSOR)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Per-axis parallel degrees. -1 on data means "absorb remaining devices"."""

    data: int = -1
    fsdp: int = 1
    stage: int = 1
    expert: int = 1
    seq: int = 1
    tensor: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        fixed = self.fsdp * self.stage * self.expert * self.seq * self.tensor
        data = self.data
        if data == -1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}")
            data = n_devices // fixed
        total = data * fixed
        if total != n_devices:
            raise ValueError(
                f"mesh {self} needs {total} devices, have {n_devices}")
        return dataclasses.replace(self, data=data)

    @property
    def shape(self) -> tuple[int, ...]:
        return (self.data, self.fsdp, self.stage, self.expert, self.seq, self.tensor)


def make_mesh(config: MeshConfig, devices: Optional[list] = None) -> Mesh:
    """Build a named Mesh over the given (default: all) devices."""
    devices = devices if devices is not None else jax.devices()
    cfg = config.resolve(len(devices))
    try:
        dev_array = mesh_utils.create_device_mesh(cfg.shape, devices=devices)
    except (ValueError, AssertionError):
        # topology-aware layout can fail for odd shapes on virtual devices —
        # fall back to a plain reshape (correct, possibly suboptimal ICI use)
        dev_array = np.asarray(devices).reshape(cfg.shape)
    return Mesh(dev_array, AXES.ALL)


def best_mesh_for(n_devices: int, *, tensor: int = 1, seq: int = 1,
                  expert: int = 1, fsdp: Optional[int] = None) -> Mesh:
    """Convenience: a sensible mesh for n devices — tensor/seq/expert as
    asked, fsdp absorbing what data-parallel doesn't need. Used by
    bench/dryrun paths. ``expert`` carves out MoE expert parallelism
    (serving: EPxTP composes, e.g. expert=4, tensor=2 on 8 chips)."""
    tensor = min(tensor, n_devices)
    remaining = n_devices // (tensor * seq * expert)
    if remaining < 1:
        raise ValueError(
            f"tensor={tensor} x seq={seq} x expert={expert} exceeds "
            f"{n_devices} devices")
    if fsdp is None:
        fsdp = remaining
    data = n_devices // (fsdp * tensor * seq * expert)
    cfg = MeshConfig(data=data, fsdp=fsdp, expert=expert, seq=seq,
                     tensor=tensor)
    return make_mesh(cfg, jax.devices()[:n_devices])


def mesh_summary(mesh: Mesh) -> str:
    parts = [f"{name}={size}" for name, size in mesh.shape.items() if size > 1]
    return ",".join(parts) or "single-device"


# -- elastic resize (ISSUE 6) --------------------------------------------------

def dp_width(mesh: Mesh) -> int:
    """The mesh's data-parallel width: the product of the batch-carrying
    axes (data x fsdp). This is the dimension elastic training resizes —
    model-parallel axes (tensor/seq/stage/expert) are pinned to the slice
    topology and never shrink on host loss."""
    return mesh.shape[AXES.DATA] * mesh.shape[AXES.FSDP]


def resize_config(config: MeshConfig, n_devices: int) -> MeshConfig:
    """The same parallelism layout over a different device count: the
    model-parallel axes (tensor/seq/stage/expert) keep their degrees, and
    data/fsdp absorb the surviving devices. FSDP shrinks proportionally
    when it can (param shards grow; memory headroom is the caller's
    problem to have provisioned), else collapses into pure data parallel.
    Raises ValueError when the survivors can't host the model axes at all
    — the caller falls back to requeueing the whole gang."""
    model = config.stage * config.expert * config.seq * config.tensor
    if n_devices < model or n_devices % model:
        raise ValueError(
            f"{n_devices} surviving devices cannot carry the model axes "
            f"(stage*expert*seq*tensor={model}); requeue instead of resizing")
    budget = n_devices // model
    fsdp = min(config.fsdp, budget)
    while fsdp > 1 and budget % fsdp:
        fsdp -= 1
    return dataclasses.replace(config, data=budget // fsdp, fsdp=fsdp)


def make_resized_mesh(config: MeshConfig, devices: list) -> Mesh:
    """Rebuild the mesh over a surviving (or restored) device list at the
    width ``resize_config`` chooses. The returned mesh uses the same axis
    names, so logical sharding rules (parallel/sharding.py) re-apply
    unchanged and an orbax restore with the new NamedShardings reshards
    params/optimizer state onto it (the PR 3 StandardRestore seam)."""
    return make_mesh(resize_config(config, len(devices)), devices)
