"""jax.distributed bootstrap from kubelet-injected env.

The workload-side consumer of gang/env.py's injection: the kubelet starts every
worker of a slice with JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID (+ MEGASCALE_* for multislice); calling initialize_from_env() at
program start forms the multi-controller runtime so ICI collectives see the
full mesh (SURVEY.md §5.8: "the kubelet must start them together and expose
slice topology; jax.distributed.initialize with a coordinator the kubelet
chooses").
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Optional

log = logging.getLogger(__name__)


@dataclasses.dataclass
class ProcessEnv:
    coordinator: str
    num_processes: int
    process_id: int
    worker_id: int
    num_slices: int
    slice_id: int
    accelerator_type: str
    topology: str

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1


def process_env_summary(env: Optional[dict] = None) -> ProcessEnv:
    e = os.environ if env is None else env
    return ProcessEnv(
        coordinator=e.get("JAX_COORDINATOR_ADDRESS", ""),
        num_processes=int(e.get("JAX_NUM_PROCESSES", "1")),
        process_id=int(e.get("JAX_PROCESS_ID", "0")),
        worker_id=int(e.get("TPU_WORKER_ID", "0")),
        num_slices=int(e.get("MEGASCALE_NUM_SLICES", "1")),
        slice_id=int(e.get("MEGASCALE_SLICE_ID", "0")),
        accelerator_type=e.get("TPU_ACCELERATOR_TYPE", ""),
        topology=e.get("TPU_TOPOLOGY", ""),
    )


def initialize_from_env(env: Optional[dict] = None, timeout_s: int = 300) -> ProcessEnv:
    """Form the multi-controller runtime if the kubelet injected gang env;
    no-op for single-process runs (local dev, single-host slices)."""
    pe = process_env_summary(env)
    if not pe.is_distributed:
        log.info("single-process run (no gang env) — skipping jax.distributed")
        return pe
    import jax
    log.info("jax.distributed.initialize(coordinator=%s, num_processes=%d, "
             "process_id=%d) [slice %d/%d]",
             pe.coordinator, pe.num_processes, pe.process_id,
             pe.slice_id, pe.num_slices)
    jax.distributed.initialize(
        coordinator_address=pe.coordinator,
        num_processes=pe.num_processes,
        process_id=pe.process_id,
        initialization_timeout=timeout_s,
    )
    return pe


# -- elastic gang resize (ISSUE 6) ---------------------------------------------

@dataclasses.dataclass
class ResizeEnv:
    """How a gang looks after an elastic shrink/grow relaunch. The kubelet
    injects the regular JAX_* vars already renumbered for the survivors
    (gang/env.py computes them over the surviving worker subset), plus:

      TPU_GANG_FULL_HOSTS   the slice's original host count
      TPU_ELASTIC_RESIZE    cumulative shrink/grow count (>0 on a resize
                            relaunch; rides the same injection path as
                            TPU_RESTART_ATTEMPT / TPU_CHECKPOINT_DIR)
      TPU_ELASTIC_BATCH_MODE  "global" (hold global batch via grad
                            accumulation) or "per_host" (hold per-host
                            batch; global batch scales with the gang)
    """

    full_hosts: int
    resize_count: int
    batch_mode: str

    @property
    def is_resized(self) -> bool:
        return self.resize_count > 0

    def shrunk(self, pe: ProcessEnv) -> bool:
        return self.is_resized and pe.num_processes < self.full_hosts


def resize_env_summary(pe: ProcessEnv, env: Optional[dict] = None) -> ResizeEnv:
    e = os.environ if env is None else env
    return ResizeEnv(
        full_hosts=int(e.get("TPU_GANG_FULL_HOSTS",
                             str(pe.num_processes)) or pe.num_processes),
        resize_count=int(e.get("TPU_ELASTIC_RESIZE", "0") or 0),
        batch_mode=e.get("TPU_ELASTIC_BATCH_MODE", "global") or "global",
    )


def surviving_process_env(pe: ProcessEnv, lost_workers: set[int],
                          my_worker_id: Optional[int] = None) -> ProcessEnv:
    """The ProcessEnv a surviving host assumes after ``lost_workers`` leave
    the gang: process ids renumbered densely over the survivors (jax wants
    a contiguous 0..n-1 process space), worker identity preserved. This is
    the SAME renumbering gang/env.py applies on a resize relaunch — shared
    here so an in-process rendezvous (single-controller runs, tests) and
    the kubelet-driven relaunch agree on who is process 0."""
    wid = pe.worker_id if my_worker_id is None else my_worker_id
    if wid in lost_workers:
        raise ValueError(f"worker {wid} is in the lost set — it has no "
                         "place in the resized gang")
    survivors = [w for w in range(pe.num_processes) if w not in lost_workers]
    return dataclasses.replace(
        pe,
        num_processes=len(survivors),
        process_id=survivors.index(wid),
        worker_id=wid,
    )


def reinitialize_from_env(env: Optional[dict] = None,
                          timeout_s: int = 300) -> ProcessEnv:
    """Tear down and re-form the multi-controller runtime after a resize:
    the surviving hosts rendezvous at the (possibly new) coordinator with
    their renumbered process ids. Single-process runs no-op, like
    initialize_from_env — the mesh rebuild alone carries the resize."""
    pe = process_env_summary(env)
    if not pe.is_distributed:
        return pe
    import jax
    try:
        jax.distributed.shutdown()
    except (RuntimeError, ValueError):
        pass  # never initialized, or the old coordinator died with the host
    log.info("elastic resize: re-forming gang (coordinator=%s, "
             "num_processes=%d, process_id=%d)",
             pe.coordinator, pe.num_processes, pe.process_id)
    jax.distributed.initialize(
        coordinator_address=pe.coordinator,
        num_processes=pe.num_processes,
        process_id=pe.process_id,
        initialization_timeout=timeout_s,
    )
    return pe
