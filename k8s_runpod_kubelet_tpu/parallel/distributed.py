"""jax.distributed bootstrap from kubelet-injected env.

The workload-side consumer of gang/env.py's injection: the kubelet starts every
worker of a slice with JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID (+ MEGASCALE_* for multislice); calling initialize_from_env() at
program start forms the multi-controller runtime so ICI collectives see the
full mesh (SURVEY.md §5.8: "the kubelet must start them together and expose
slice topology; jax.distributed.initialize with a coordinator the kubelet
chooses").
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Optional

log = logging.getLogger(__name__)


@dataclasses.dataclass
class ProcessEnv:
    coordinator: str
    num_processes: int
    process_id: int
    worker_id: int
    num_slices: int
    slice_id: int
    accelerator_type: str
    topology: str

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1


def process_env_summary(env: Optional[dict] = None) -> ProcessEnv:
    e = os.environ if env is None else env
    return ProcessEnv(
        coordinator=e.get("JAX_COORDINATOR_ADDRESS", ""),
        num_processes=int(e.get("JAX_NUM_PROCESSES", "1")),
        process_id=int(e.get("JAX_PROCESS_ID", "0")),
        worker_id=int(e.get("TPU_WORKER_ID", "0")),
        num_slices=int(e.get("MEGASCALE_NUM_SLICES", "1")),
        slice_id=int(e.get("MEGASCALE_SLICE_ID", "0")),
        accelerator_type=e.get("TPU_ACCELERATOR_TYPE", ""),
        topology=e.get("TPU_TOPOLOGY", ""),
    )


def initialize_from_env(env: Optional[dict] = None, timeout_s: int = 300) -> ProcessEnv:
    """Form the multi-controller runtime if the kubelet injected gang env;
    no-op for single-process runs (local dev, single-host slices)."""
    pe = process_env_summary(env)
    if not pe.is_distributed:
        log.info("single-process run (no gang env) — skipping jax.distributed")
        return pe
    import jax
    log.info("jax.distributed.initialize(coordinator=%s, num_processes=%d, "
             "process_id=%d) [slice %d/%d]",
             pe.coordinator, pe.num_processes, pe.process_id,
             pe.slice_id, pe.num_slices)
    jax.distributed.initialize(
        coordinator_address=pe.coordinator,
        num_processes=pe.num_processes,
        process_id=pe.process_id,
        initialization_timeout=timeout_s,
    )
    return pe
