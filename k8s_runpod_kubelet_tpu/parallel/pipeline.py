"""Pipeline parallelism over the ``stage`` mesh axis — pure GSPMD, no shard_map.

Reference parity note: the reference (BSVogler/k8s-runpod-kubelet) has no
parallelism code at all (SURVEY.md §2.4 absence table, "Pipeline parallel:
No"); this is net-new TPU capability bringing the reserved ``stage`` axis of
parallel/mesh.py to life.

Design (the MaxText/GSPMD pattern, not a torch send/recv transliteration):
- Layer params keep their stacked (L, ...) layout; L = n_stages · R splits
  into a leading stage dim sharded over the ``stage`` mesh axis, so each
  stage's R layers live on that stage's devices.
- The activation state is a (n_stages, microbatch, ...) buffer, stage-sharded
  on dim 0. One scan step applies EVERY stage in parallel (vmap over the
  stage dim) to the microbatch it currently holds — classic GPipe schedule,
  all stages busy once the pipeline fills.
- The inter-stage hop is ``jnp.roll`` along the stage-sharded dim, which XLA
  lowers to a collective-permute over ICI. No explicit comm code.
- Bubble steps compute on zeros; their outputs are never observed: the output
  buffer is written in increasing microbatch order so the last (always valid)
  write wins, and router-aux contributions are masked by the fill schedule.

Because shardings never change values under GSPMD, the pipelined forward is
bitwise-semantically the plain scan-over-layers forward — tested against it.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import AXES


def pipeline_stages(mesh: Optional[Mesh]) -> int:
    return int(mesh.shape.get(AXES.STAGE, 1)) if mesh is not None else 1


def pipeline_spmd(layer_params: Any, x: jax.Array, stage_fn: Callable, *,
                  mesh: Mesh, n_microbatches: Optional[int] = None
                  ) -> tuple[jax.Array, jax.Array]:
    """Run scan-stacked layers as a GPipe pipeline over the ``stage`` axis.

    - ``layer_params``: pytree, every leaf with leading (L, ...) layer axis.
    - ``x``: embedded activations (B, ...) — batch leads.
    - ``stage_fn(stage_layers, x_mb) -> (y_mb, aux)``: applies one stage's
      (R, ...) layers to one microbatch; ``aux`` is a scalar MEAN-style loss
      over that microbatch's tokens (router losses are means).
    Returns (y (B, ...), aux averaged over microbatches — i.e. the same
    full-batch mean the plain scan forward would produce).
    """
    n_stages = pipeline_stages(mesh)
    lead = jax.tree_util.tree_leaves(layer_params)[0].shape[0]
    if lead % n_stages:
        raise ValueError(f"n_layers={lead} not divisible by {n_stages} stages")
    m = n_microbatches or n_stages
    b = x.shape[0]
    if b % m:
        raise ValueError(f"batch={b} not divisible by {m} microbatches")

    rep = lead // n_stages
    stages = jax.tree_util.tree_map(
        lambda p: p.reshape(n_stages, rep, *p.shape[1:]), layer_params)
    xm = x.reshape(m, b // m, *x.shape[1:])

    data_axes = (AXES.DATA, AXES.FSDP)
    trail = (None,) * (x.ndim - 1)
    buf_spec = NamedSharding(mesh, P(AXES.STAGE, data_axes, *trail))
    out_spec = NamedSharding(mesh, P(None, data_axes, *trail))

    buf = jnp.zeros((n_stages, *xm.shape[1:]), x.dtype).at[0].set(xm[0])
    out = jnp.zeros_like(xm)
    vstage = jax.vmap(stage_fn)

    def step(carry, t):
        buf, out = carry
        y, aux = vstage(stages, buf)
        # stage s is working on microbatch (t - s); mask the bubble auxes
        mb_of_stage = t - jnp.arange(n_stages)
        valid = (mb_of_stage >= 0) & (mb_of_stage < m)
        aux_sum = jnp.sum(jnp.where(valid, aux, 0.0))
        # last stage finished microbatch t-(S-1). Early (t < S-1) writes land
        # on clipped index 0 with bubble garbage — overwritten by the valid
        # write at t = S-1, since writes hit each index in increasing order.
        out = jax.lax.dynamic_update_index_in_dim(
            out, y[-1], jnp.clip(t - (n_stages - 1), 0, m - 1), 0)
        # the inter-stage hop: roll along the stage-sharded dim = ppermute.
        # Stage 0's rolled-in value is replaced by the next microbatch feed
        # (past the last microbatch it re-feeds mb m-1; those outputs never
        # reach the last stage within the loop, so they're unobservable).
        buf = jnp.roll(y, 1, axis=0).at[0].set(xm[jnp.clip(t + 1, 0, m - 1)])
        buf = jax.lax.with_sharding_constraint(buf, buf_spec)
        out = jax.lax.with_sharding_constraint(out, out_spec)
        return (buf, out), aux_sum

    (_, out), auxes = jax.lax.scan(
        step, (buf, out), jnp.arange(m + n_stages - 1))
    # each microbatch contributed a per-token-mean aux at every stage; dividing
    # by M recovers the full-batch mean the unpipelined forward computes
    return out.reshape(b, *x.shape[1:]), jnp.sum(auxes) / m
