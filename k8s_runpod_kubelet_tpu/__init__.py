"""TPU-native virtual kubelet + workload framework.

A brand-new framework with the capability surface of BSVogler/k8s-runpod-kubelet
(reference at /root/reference, surveyed in SURVEY.md), rebuilt TPU-first:

- ``cloud/``     L1': Cloud TPU client (QueuedResources) — the TPU-native analog of the
                 reference's RunPod REST/GraphQL client (runpod_client.go).
- ``kube/``      Minimal Kubernetes API client + hermetic in-memory fake.
- ``node/``      L3': node registration, lease heartbeat, pod-watch controller, kubelet
                 HTTP API — replaces the external virtual-kubelet library the reference
                 leans on (go.mod:53).
- ``provider/``  L2': pod lifecycle, spec translation, status translation, reconcile
                 loops, cleanup & crash recovery (kubelet.go).
- ``gang/``      Net-new: multi-host slice gang scheduling, per-worker env injection and
                 exec/log transport (SURVEY.md §2.4, §5.8).
- ``parallel/``  Device-mesh + sharding utilities (dp/fsdp/tp/sp/pp/ep), jax.distributed
                 bootstrap from kubelet-injected env.
- ``models/``    Flagship workloads: Llama-family transformer, MNIST, Gemma serving cfg.
- ``ops/``       TPU kernels: flash/ring attention (Pallas with XLA fallback), rmsnorm,
                 rotary embeddings.
- ``workloads/`` Training step (optax/orbax) and a JetStream-style serving engine.

Control-plane modules import no JAX so the kubelet stays lightweight; the workload
stack is imported lazily.
"""

__version__ = "0.1.0"
