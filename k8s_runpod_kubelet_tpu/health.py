"""Health + metrics + debug server.

Parity with the reference's standalone health server (health.go:1-74:
/healthz = liveness flag, /readyz = flag AND readyFunc — wired to provider.Ping
at main.go:397-402), plus the observability surface the reference lacks
entirely (SURVEY.md §5.5):

  /metrics       Prometheus text (counters/gauges/histograms)
  /debug/traces  recent finished spans as JSON; ?trace_id= filters to one
                 trace (the span tree a traceparent header names)
  /debug/engine  statusz-style snapshot from the injected callable (the
                 serving engine's in-flight slots / queue / cache occupancy;
                 404 when the process has no engine, e.g. the kubelet)
  /debug/train   training-telemetry statusz from the injected callable: the
                 goodput ledger buckets, step/MFU stats, per-host watchdog
                 table on a training worker-0 — or, on the kubelet, the
                 per-pod telemetry the reconcile loop scraped (ISSUE 5)
  /heartbeat     POST (training worker-0 only): peers' step-heartbeat
                 protocol lines, fed to the straggler watchdog
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from .metrics import Metrics
from .tracing import Tracer

log = logging.getLogger(__name__)


class _Handler(BaseHTTPRequestHandler):
    server_ref: "HealthServer"

    def log_message(self, *a):
        pass

    def _send(self, status: int, body: bytes, ctype: str = "text/plain"):
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, status: int, payload) -> None:
        self._send(status, json.dumps(payload).encode(), "application/json")

    def do_GET(self):
        hs = self.server_ref
        path = urllib.parse.urlparse(self.path)
        if path.path == "/healthz":
            if hs.healthy.is_set():
                return self._send(200, b"ok")
            return self._send(503, b"unhealthy")
        if path.path == "/readyz":
            ready = hs.healthy.is_set()
            if ready and hs.ready_func is not None:
                try:
                    ready = bool(hs.ready_func())
                except Exception as e:  # noqa: BLE001
                    log.warning("readyz probe errored: %s", e)
                    ready = False
            return self._send(200 if ready else 503,
                              b"ready" if ready else b"not ready")
        if path.path == "/metrics" and hs.metrics is not None:
            return self._send(200, hs.metrics.render().encode(),
                              "text/plain; version=0.0.4")
        if path.path == "/debug/traces" and hs.tracer is not None:
            q = urllib.parse.parse_qs(path.query)
            return self._send_json(200, hs.tracer.query(
                (q.get("trace_id") or [""])[0]))
        if path.path == "/debug/engine" and hs.engine_status is not None:
            try:
                return self._send_json(200, hs.engine_status())
            except Exception as e:  # noqa: BLE001 — debug must not 500-loop
                return self._send_json(500, {"error": str(e)})
        if path.path == "/debug/train" and hs.train_status is not None:
            try:
                return self._send_json(200, hs.train_status())
            except Exception as e:  # noqa: BLE001 — debug must not 500-loop
                return self._send_json(500, {"error": str(e)})
        self._send(404, b"not found")

    def do_POST(self):
        hs = self.server_ref
        path = urllib.parse.urlparse(self.path)
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        if path.path == "/heartbeat" and hs.heartbeat_sink is not None:
            try:
                hs.heartbeat_sink(body.decode("utf-8", errors="replace"))
            except Exception as e:  # noqa: BLE001 — a bad beat must not 500-loop
                log.debug("heartbeat ingest failed: %s", e)
                return self._send_json(400, {"error": str(e)})
            return self._send_json(200, {"ok": True})
        self._send(404, b"not found")


class HealthServer:
    def __init__(self, address: str = ":8080",
                 ready_func: Optional[Callable[[], bool]] = None,
                 metrics: Optional[Metrics] = None,
                 tracer: Optional[Tracer] = None,
                 engine_status: Optional[Callable[[], dict]] = None,
                 train_status: Optional[Callable[[], dict]] = None,
                 heartbeat_sink: Optional[Callable[[str], None]] = None):
        host, _, port = address.rpartition(":")
        self.ready_func = ready_func
        self.metrics = metrics
        self.tracer = tracer
        self.engine_status = engine_status
        self.train_status = train_status
        self.heartbeat_sink = heartbeat_sink
        self.healthy = threading.Event()
        self.healthy.set()
        handler = type("BoundHandler", (_Handler,), {"server_ref": self})
        self._httpd = ThreadingHTTPServer((host or "0.0.0.0", int(port)), handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="health-server", daemon=True)

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "HealthServer":
        self._thread.start()
        log.info("health server on :%d (/healthz /readyz /metrics "
                 "/debug/traces /debug/engine /debug/train)", self.port)
        return self

    @property
    def started(self) -> bool:
        return self._thread.is_alive()

    def set_healthy(self, healthy: bool):
        if healthy:
            self.healthy.set()
        else:
            self.healthy.clear()

    def stop(self):
        # shutdown() deadlocks if serve_forever never ran — only call it on a
        # live server thread
        if self._thread.is_alive():
            self._httpd.shutdown()
        self._httpd.server_close()
