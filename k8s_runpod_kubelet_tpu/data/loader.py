"""Training-data input pipeline: native C++ loader + pure-Python fallback.

Binds native/tokenloader.cc via ctypes (built on demand with g++ — no build
system or pip dependency). Both implementations produce the *identical*
deterministic batch stream for a given (seed, seq_len, batch, shard) tuple:
the native one from background threads with a reorder buffer, the Python one
inline. Parity is asserted in tests/test_data_loader.py, so either path can
serve any worker.

Data format: raw little-endian int32 token stream on disk (pre-tokenized
corpus, MaxText-style). ``path=None`` gives the synthetic xorshift stream used
by benches — infinite, seeded, no disk.

SPMD sharding: every worker process opens its own (shard_id, num_shards)
loader and reads a disjoint window range — no cross-host data coordination,
matching the same-program-own-shard model the gang scheduler sets up
(gang/env.py).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
import threading
import weakref
from typing import Iterator, Optional

import numpy as np

log = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native", "tokenloader.cc")
_LIB_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB = os.path.join(_LIB_DIR, "_tokenloader.so")

_build_lock = threading.Lock()
_lib_handle = None
_MASK64 = (1 << 64) - 1


def _build_native() -> Optional[str]:
    """Compile the loader with g++ if the .so is missing/stale. None if no
    toolchain — callers fall back to the Python path."""
    try:
        src_mtime = os.path.getmtime(_SRC)
    except OSError:
        return _LIB if os.path.exists(_LIB) else None
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= src_mtime:
        return _LIB
    with _build_lock:
        if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= src_mtime:
            return _LIB
        tmp = tempfile.mktemp(suffix=".so", dir=_LIB_DIR)
        cmd = ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
               _SRC, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, _LIB)  # atomic: concurrent builders see old or new
        except (OSError, subprocess.SubprocessError) as exc:
            log.warning("native tokenloader build failed (%s); "
                        "using Python fallback", exc)
            if os.path.exists(tmp):
                os.unlink(tmp)
            return None
    return _LIB


def _native_lib():
    global _lib_handle
    if _lib_handle is not None:
        return _lib_handle
    path = _build_native()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError as exc:
        # corrupt/ABI-stale cached .so (image/arch change, disk-full
        # truncation): drop it so a later call rebuilds; fall back for now
        log.warning("cached %s unloadable (%s); using Python fallback",
                    path, exc)
        try:
            os.unlink(path)
        except OSError:
            pass
        return None
    lib.tl_open.restype = ctypes.c_void_p
    lib.tl_open.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
                            ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32,
                            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                            ctypes.c_uint64]
    lib.tl_next.restype = ctypes.c_int32
    lib.tl_next.argtypes = [ctypes.c_void_p,
                            ctypes.POINTER(ctypes.c_int32)]
    lib.tl_num_tokens.restype = ctypes.c_int64
    lib.tl_num_tokens.argtypes = [ctypes.c_void_p]
    lib.tl_batches_per_epoch.restype = ctypes.c_int64
    lib.tl_batches_per_epoch.argtypes = [ctypes.c_void_p]
    lib.tl_close.restype = None
    lib.tl_close.argtypes = [ctypes.c_void_p]
    _lib_handle = lib
    return lib


def native_available() -> bool:
    return _native_lib() is not None


class NativeTokenLoader:
    """Background-threaded batch producer over the C++ loader."""

    def __init__(self, path: Optional[str], seq_len: int, batch_size: int,
                 seed: int = 0, vocab_size: int = 32768, threads: int = 2,
                 capacity: int = 8, shard_id: int = 0, num_shards: int = 1,
                 start_batch: int = 0):
        lib = _native_lib()
        if lib is None:
            raise RuntimeError("native tokenloader unavailable (no g++?)")
        self._lib = lib
        self.seq_len = seq_len
        self.batch_size = batch_size
        # wrong-tokenizer guard applies to file corpora only: the synthetic
        # stream emits `s % vocab`, in range by construction — don't pay a
        # per-batch scan on the consumer thread for it
        self._check_range = bool(path)
        self._vocab_size = vocab_size
        self._h = lib.tl_open(path.encode() if path else None, seq_len,
                              batch_size, seed & _MASK64, threads, capacity,
                              vocab_size, shard_id, num_shards, start_batch)
        if not self._h:
            raise ValueError(
                f"tl_open failed: path={path!r} seq_len={seq_len} "
                f"batch={batch_size} shard={shard_id}/{num_shards} "
                "(missing/short file, or shard smaller than one batch?)")
        # safety net for loaders dropped without close(): otherwise the C++
        # worker threads, mmap, and fd leak for the process lifetime
        self._finalizer = weakref.finalize(self, lib.tl_close, self._h)

    @property
    def num_tokens(self) -> int:
        return self._lib.tl_num_tokens(self._h)

    @property
    def batches_per_epoch(self) -> int:
        return self._lib.tl_batches_per_epoch(self._h)

    def next(self) -> np.ndarray:
        out = np.empty((self.batch_size, self.seq_len + 1), np.int32)
        rc = self._lib.tl_next(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc != 0:
            raise RuntimeError("tokenloader stopped")
        if self._check_range:
            _check_token_range(out, self._vocab_size)
        return out

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.next()

    def close(self):
        if self._h:
            self._finalizer.detach()
            self._lib.tl_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _check_token_range(batch: np.ndarray, vocab_size: int):
    """A corpus tokenized with a bigger-vocab tokenizer must fail loudly:
    jnp.take/one_hot clamp or zero out-of-range ids, which would otherwise
    train silently on garbage embeddings."""
    lo, hi = int(batch.min()), int(batch.max())
    if lo < 0 or hi >= vocab_size:
        raise ValueError(
            f"corpus token id range [{lo}, {hi}] outside model vocab "
            f"[0, {vocab_size}) — wrong tokenizer for this model?")


def _splitmix64(x: int) -> int:
    x = (x + 0x9E3779B97F4A7C15) & _MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK64
    return x ^ (x >> 31)


class PyTokenLoader:
    """Pure-Python twin of NativeTokenLoader — bit-identical stream."""

    def __init__(self, path: Optional[str], seq_len: int, batch_size: int,
                 seed: int = 0, vocab_size: int = 32768, threads: int = 0,
                 capacity: int = 0, shard_id: int = 0, num_shards: int = 1,
                 start_batch: int = 0):
        del threads, capacity  # signature parity with the native loader
        if not (0 <= shard_id < num_shards):
            raise ValueError(f"shard_id {shard_id} not in [0, {num_shards})")
        self.seq_len, self.batch_size = seq_len, batch_size
        self.seed = seed & _MASK64
        self.vocab_size, self.shard_id = vocab_size, shard_id
        self._tokens: Optional[np.ndarray] = None
        if path:
            # memmap, not fromfile: the fallback must handle multi-GB corpora
            # with the same lazy paging as the native mmap path
            self._tokens = np.memmap(path, np.int32, mode="r")
            if self._tokens.size < seq_len + 1:
                raise ValueError(f"{path}: fewer than seq_len+1 tokens")
            total_windows = (self._tokens.size - 1) // seq_len
        else:
            total_windows = 1 << 40
        self._shard_windows = (total_windows // num_shards
                               if num_shards > 1 else total_windows)
        if self._shard_windows < batch_size:
            raise ValueError(f"shard has {self._shard_windows} windows < "
                             f"batch {batch_size}")
        self._i = start_batch

    @property
    def num_tokens(self) -> int:
        return self._shard_windows * self.seq_len if self._tokens is not None else -1

    @property
    def batches_per_epoch(self) -> int:
        return self._shard_windows // self.batch_size

    def _window_for(self, gs: int) -> int:
        # cycle-walked affine bijection — must mirror tokenloader.cc WindowFor
        n = self._shard_windows
        m = 1
        while m < n:
            m <<= 1
        epoch, i = divmod(gs, n)
        sh = (self.shard_id * 0x9E3779B97F4A7C15) & _MASK64
        a = _splitmix64(self.seed ^ ((epoch * 2654435761) & _MASK64) ^ sh) | 1
        b = _splitmix64((self.seed + epoch + 0x51ED270B + sh) & _MASK64)
        w = i
        while True:
            w = ((a * w + b) & _MASK64) & (m - 1)
            if w < n:
                break
        return w + self.shard_id * self._shard_windows

    def _fill(self, gs: int, dst: np.ndarray):
        span = self.seq_len + 1
        if self._tokens is not None:
            w = self._window_for(gs)
            dst[:] = self._tokens[w * self.seq_len: w * self.seq_len + span]
        else:
            s = _splitmix64(self.seed ^ ((gs * 0x9E3779B9) & _MASK64)
                            ^ ((self.shard_id << 48) & _MASK64))
            for t in range(span):
                s = (s ^ (s << 13)) & _MASK64
                s ^= s >> 7
                s = (s ^ (s << 17)) & _MASK64
                dst[t] = s % self.vocab_size  # vocab < 2^31 keeps this in int32

    def next(self) -> np.ndarray:
        out = np.empty((self.batch_size, self.seq_len + 1), np.int32)
        for s in range(self.batch_size):
            self._fill(self._i * self.batch_size + s, out[s])
        self._i += 1
        if self._tokens is not None:
            _check_token_range(out, self.vocab_size)
        return out

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.next()

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def make_loader(path: Optional[str], seq_len: int, batch_size: int, **kw):
    """Native if buildable, else Python — identical stream either way."""
    if native_available():
        return NativeTokenLoader(path, seq_len, batch_size, **kw)
    return PyTokenLoader(path, seq_len, batch_size, **kw)


def device_batches(loader, mesh=None) -> Iterator:
    """Adapts a loader to the Trainer: device_put on the data axes.

    Multi-host: each process's loader holds a disjoint shard and yields its
    *local* rows (global_batch / num_processes); the global array is assembled
    with make_array_from_process_local_data so every shard's stream is
    consumed exactly once — a plain device_put of per-host-different data
    would silently keep only the addressable rows of each host's copy.
    """
    import jax
    from ..parallel.sharding import logical_sharding
    if mesh is None:
        for batch in loader:
            yield jax.numpy.asarray(batch)
        return
    sharding = logical_sharding(mesh, ("batch", None))
    if jax.process_count() == 1:
        for batch in loader:
            yield jax.device_put(batch, sharding)
        return
    for batch in loader:
        global_shape = (batch.shape[0] * jax.process_count(), batch.shape[1])
        yield jax.make_array_from_process_local_data(sharding, batch,
                                                     global_shape)
