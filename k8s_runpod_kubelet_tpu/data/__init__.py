"""Input pipeline: native (C++) and Python token-batch loaders."""

from .loader import (NativeTokenLoader, PyTokenLoader, device_batches,
                     make_loader, native_available)

__all__ = ["NativeTokenLoader", "PyTokenLoader", "device_batches",
           "make_loader", "native_available"]
