"""Global prefix directory (ISSUE 16): the fleet-level map from prefix
keys to the replicas whose arenas hold those pages.

PR 11 left every replica's prefix trie an island: the router's rendezvous
affinity was the ONLY mechanism keeping a conversation near its cached
KV, and a flash crowd spilling over affinity re-prefilled the same system
prompt on every replica it touched. The directory makes cached KV a
fleet-wide asset: replicas publish the page-aligned prefixes they hold
(on trie insert, carried by their heartbeats), the router consults the
directory when the replica it picked is not a holder, and plans a PULL
hop — the cold replica fetches the page run from a holder over the
fastest reachable rung instead of recomputing it. Rendezvous affinity
becomes an optimization, not a correctness crutch.

**Keys.** A prefix key is an incremental SHA-256 over the page-sized
token chunks of a prompt, seeded with the page size and the adapter
root (``prefix_key_chain``). Both sides of the fabric compute it
identically: the engine keys what it inserts, the router keys the
request it is about to route — chunk hashing makes every page boundary
of a longer prompt yield the key a shorter cached prefix published
under, so one published key serves every request that extends it. The
MODEL is deliberately NOT in the key: the router does not know the
fleet's model name, so entries carry it as data instead and the pull
doors reject cross-model adoption exactly like ``adopt_handoff`` does
(``deserialize_pages``' expect_model, twice: once at the export door,
once at adoption).

**Lifecycle.** publish (trie insert / adoption, via heartbeat) → hit
(router lookup on a directory-keyed request) → invalidate (a pull that
came back GONE — the holder's trie evicted the pages since publish — or
the holder leaving the fleet: eviction, drain, deregistration drop ALL
of a replica's entries in the same registry transaction). Entries are a
bounded LRU: the directory is a routing cache over heartbeat-refreshed
claims, never the source of truth — a stale entry costs one failed pull
that falls back to prefill, nothing worse.

Thread-safe, clock-injected, numpy/jax-free: it lives in the registry
tier next to ReplicaRegistry and must be importable by tier-1 tests and
the router without a device runtime.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

_KEY_SEED = "tpukvf1"


def prefix_key_chain(tokens: list, page_tokens: int,
                     adapter: str = "") -> list[str]:
    """One key per FULL-page boundary of ``tokens``, shortest first:
    ``keys[i]`` covers pages ``0..i`` (``(i + 1) * page_tokens`` tokens).
    Incremental hashing means a prompt's chain contains, as a prefix,
    the chain of every shorter prompt it extends — so a holder
    publishing its run's LONGEST key is findable from any longer
    request's chain. The seed binds page size and adapter root: a
    fleet re-paged at a different granule (or another adapter's
    variant pages) can never alias."""
    if page_tokens < 1:
        raise ValueError(f"page_tokens must be >= 1, got {page_tokens}")
    h = hashlib.sha256(f"{_KEY_SEED}|{page_tokens}|{adapter}".encode())
    keys = []
    for start in range(0, len(tokens) - page_tokens + 1, page_tokens):
        chunk = tokens[start:start + page_tokens]
        h.update(",".join(str(int(t)) for t in chunk).encode())
        keys.append(h.copy().hexdigest()[:32])
    return keys


def prefix_key(tokens: list, page_tokens: int, adapter: str = "") -> str:
    """The longest-boundary key of ``tokens`` (what a holder publishes
    for an inserted run); "" when the run is shorter than one page."""
    chain = prefix_key_chain(tokens, page_tokens, adapter)
    return chain[-1] if chain else ""


class _Entry:
    __slots__ = ("pages", "model", "adapter", "holders")

    def __init__(self, pages: int, model: str, adapter: str):
        self.pages = pages
        self.model = model
        self.adapter = adapter
        self.holders: dict[str, float] = {}   # replica_id -> published_at

    def to_dict(self) -> dict:
        return {"pages": self.pages, "model": self.model,
                "adapter": self.adapter,
                "holders": sorted(self.holders)}


class PrefixDirectory:
    """Bounded-LRU prefix-key -> {holders, pages, model, adapter-root}
    map. ``publish`` upserts a holder claim, ``lookup`` walks a request's
    key chain longest-first to the first entry with a holder,
    ``invalidate`` drops ONE holder claim (a pull that came back gone),
    ``drop_replica`` drops every claim a departing replica made — the
    registry calls it inside evict/deregister/drain so directory and
    membership can never disagree for longer than one call."""

    def __init__(self, metrics=None, max_entries: int = 4096,
                 clock: Callable[[], float] = time.monotonic):
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self.metrics = metrics
        self.max_entries = max_entries
        self.clock = clock
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        if metrics is not None:
            self._describe(metrics)
            # scrape-from-start: the series exist before the first publish
            metrics.set_gauge("tpu_fleet_prefix_directory_entries", 0)
            metrics.incr("tpu_fleet_prefix_directory_hits", 0)
            metrics.incr("tpu_fleet_prefix_directory_invalidations", 0,
                         labels={"reason": "gone"})

    @staticmethod
    def _describe(m):
        m.describe("tpu_fleet_prefix_directory_entries",
                   "prefix keys the global directory currently maps to at "
                   "least one holder replica")
        m.describe("tpu_fleet_prefix_directory_hits",
                   "directory lookups that found a published entry for the "
                   "request's prefix chain")
        m.describe("tpu_fleet_prefix_directory_invalidations",
                   "holder claims dropped from the directory (labels: "
                   "reason=gone|departed — gone: a pull found the holder's "
                   "trie no longer has the pages; departed: the holder was "
                   "evicted/drained/deregistered)")

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def _gauge(self):
        if self.metrics is not None:
            self.metrics.set_gauge("tpu_fleet_prefix_directory_entries",
                                   len(self._entries))

    def publish(self, replica_id: str, publishes: list) -> int:
        """Upsert holder claims. Each publish is a dict with ``key``
        (required), ``pages``, ``model``, ``adapter``. Returns how many
        claims landed; malformed items are skipped (heartbeats carry
        these — one bad item must not poison the beat)."""
        if not replica_id:
            return 0
        now = self.clock()
        landed = 0
        with self._lock:
            for pub in publishes or []:
                if not isinstance(pub, dict):
                    continue
                key = pub.get("key")
                if not isinstance(key, str) or not key:
                    continue
                entry = self._entries.get(key)
                if entry is None:
                    entry = self._entries[key] = _Entry(
                        int(pub.get("pages") or 0),
                        str(pub.get("model") or ""),
                        str(pub.get("adapter") or ""))
                entry.holders[replica_id] = now
                self._entries.move_to_end(key)
                landed += 1
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
            self._gauge()
        return landed

    def lookup(self, keys: list) -> Optional[tuple[str, dict]]:
        """First entry (with at least one holder) along ``keys`` — the
        caller passes the request's chain LONGEST-FIRST so the deepest
        cached prefix wins. Returns (key, entry dict with ``holders`` as
        a sorted list) or None; a hit counts the hits series and
        refreshes the entry's LRU position."""
        with self._lock:
            for key in keys or []:
                entry = self._entries.get(key)
                if entry is not None and entry.holders:
                    self._entries.move_to_end(key)
                    out = (key, entry.to_dict())
                    break
            else:
                return None
        if self.metrics is not None:
            self.metrics.incr("tpu_fleet_prefix_directory_hits")
        return out

    def invalidate(self, key: str, replica_id: str,
                   reason: str = "gone") -> bool:
        """Drop ONE holder claim (the pull found it stale); the entry
        itself dies with its last holder. Returns whether a claim was
        actually dropped (idempotent — a raced double-invalidate must
        not double-count)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None or replica_id not in entry.holders:
                return False
            del entry.holders[replica_id]
            if not entry.holders:
                del self._entries[key]
            self._gauge()
        if self.metrics is not None:
            self.metrics.incr("tpu_fleet_prefix_directory_invalidations",
                              labels={"reason": reason})
        return True

    def drop_replica(self, replica_id: str) -> int:
        """Drop EVERY claim ``replica_id`` holds — the registry's
        evict/deregister/drain transaction. Returns claims dropped;
        counted under reason=departed."""
        dropped = 0
        with self._lock:
            dead = []
            for key, entry in self._entries.items():
                if replica_id in entry.holders:
                    del entry.holders[replica_id]
                    dropped += 1
                    if not entry.holders:
                        dead.append(key)
            for key in dead:
                del self._entries[key]
            self._gauge()
        if dropped and self.metrics is not None:
            self.metrics.incr("tpu_fleet_prefix_directory_invalidations",
                              dropped, labels={"reason": "departed"})
        return dropped

    def snapshot(self) -> dict:
        """The /debug/fleet ``directory`` payload: every entry with its
        holders (bounded by max_entries, so this is scrape-safe)."""
        with self._lock:
            return {"entries": {k: e.to_dict()
                                for k, e in self._entries.items()},
                    "size": len(self._entries),
                    "max_entries": self.max_entries}
