"""KV page-run handoff codec: the wire format between a prefill replica's
arena and a decode replica's (disaggregated serving, ROADMAP item 2).

A handoff moves the KV of a prompt's FULL pages — exactly what the paged
prefix pool already treats as the shareable unit — from the replica that
computed them to the replica that will decode against them. The decode
side adopts the pages into its own arena through the prefix trie, so the
engine's normal prompt match then references them zero-copy and only the
sub-page tail recomputes.

Wire format (one blob, streamable over the existing HTTP surface):

    MAGIC(6) | u32 header_len | header JSON | section payloads

The header carries ``version``, ``page_tokens``, ``n_pages``, the token
ids the pages cover (the trie key — adoption is meaningless without
them), and per-section name/dtype/shape/byte-length. Section payloads
follow in header order as C-contiguous bytes. The codec is generic over
the section dict, so plain K/V, int8-KV (scales page alongside) and MLA
latent layouts all serialize through the same two functions — layout
differences are just different section names/shapes, validated on the
receiving side against the adopting arena.

Validation is deliberately paranoid: a truncated stream, a bad magic, a
future version, a page-size or dtype mismatch each raise a typed
``HandoffError`` — the router treats any of them as a failed handoff and
falls back, never half-adopting KV.

numpy-only on purpose (no jax import): the codec must be usable by the
router tier and by tier-1 tests without touching a device runtime.
bfloat16 rides numpy's ml_dtypes registration (jax ships it).
"""

from __future__ import annotations

import json
import time
from typing import Callable, Optional

import numpy as np

MAGIC = b"TPUKV\x01"
VERSION = 1
# streaming handoff (ISSUE 10): sequence-numbered CHUNK FRAMES, each
# wrapping one page-run blob, pushed while the next prefill chunk is
# still computing. Distinct magic so a whole-run blob can never be fed
# to the stream path (or vice versa) silently.
CHUNK_MAGIC = b"TPUKC\x01"
CHUNK_VERSION = 1
# refuse absurd headers before json.loads touches them (a corrupt length
# prefix must not allocate gigabytes)
_MAX_HEADER_BYTES = 16 * 1024 * 1024


class HandoffError(ValueError):
    """A KV handoff blob that must not be adopted (truncated, foreign
    version, or shaped for a different arena). Callers treat it as a
    failed handoff and fall back to a full prefill."""


class KVPullMiss(HandoffError):
    """A /kv_pull export found the owner's trie no longer holds the
    requested page run (evicted since the directory publish). The door
    answers 404 {"gone": true}; the router invalidates the directory
    entry and the puller falls back to prefill — one miss, no retry."""


def _dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 and friends register with numpy when ml_dtypes imports
        import ml_dtypes  # noqa: F401 — import registers the dtypes
        return np.dtype(name)


def serialize_pages(tokens: list, page_tokens: int,
                    sections: dict[str, np.ndarray],
                    model: str = "") -> bytes:
    """Pack a page run into one blob. ``sections[name]`` is the page
    payload for one arena section, shaped ``(L, n_pages, page_tokens,
    ...)`` — i.e. the arena section sliced to the run's page ids, in
    prompt order. ``tokens`` are the token ids those pages hold
    (``n_pages * page_tokens`` of them)."""
    if not sections:
        raise HandoffError("no sections to serialize")
    n_pages = next(iter(sections.values())).shape[1]
    if len(tokens) != n_pages * page_tokens:
        raise HandoffError(
            f"token count {len(tokens)} != n_pages {n_pages} * "
            f"page_tokens {page_tokens}")
    metas = []
    payloads = []
    for name, arr in sections.items():
        arr = np.ascontiguousarray(arr)
        if arr.ndim < 3 or arr.shape[1] != n_pages \
                or arr.shape[2] != page_tokens:
            raise HandoffError(
                f"section {name!r} shape {arr.shape} is not "
                f"(L, {n_pages}, {page_tokens}, ...)")
        raw = arr.tobytes()
        metas.append({"name": name, "dtype": arr.dtype.name,
                      "shape": list(arr.shape), "bytes": len(raw)})
        payloads.append(raw)
    header = json.dumps({
        "version": VERSION, "page_tokens": page_tokens, "n_pages": n_pages,
        "tokens": [int(t) for t in tokens], "model": model,
        "sections": metas}).encode()
    return b"".join([MAGIC, len(header).to_bytes(4, "big"), header]
                    + payloads)


def deserialize_pages(blob: bytes, *,
                      expect_page_tokens: Optional[int] = None,
                      expect_sections: Optional[dict] = None,
                      expect_model: Optional[str] = None
                      ) -> tuple[dict, dict[str, np.ndarray]]:
    """Unpack a handoff blob into (header dict, {name: array}).

    ``expect_page_tokens`` rejects a run paged at a different granule
    (the pages could not be re-chunked without re-deriving positions);
    ``expect_sections`` maps section name -> (dtype name, per-page
    trailing shape) — the adopting arena's layout — and rejects missing/
    extra sections, dtype mismatches, and trailing-shape mismatches.
    ``expect_model`` rejects KV computed by a DIFFERENT model whose
    arena geometry happens to match (e.g. two checkpoints of one
    architecture during a rollout) — adopting it would serve garbage
    completions with no error, and the poisoned pages would stay cached.
    Every failure mode raises HandoffError with the reason."""
    if len(blob) < len(MAGIC) + 4:
        raise HandoffError(f"truncated blob: {len(blob)} bytes is shorter "
                           "than the fixed header")
    if blob[:len(MAGIC)] != MAGIC:
        raise HandoffError("bad magic: not a KV handoff blob")
    hlen = int.from_bytes(blob[len(MAGIC):len(MAGIC) + 4], "big")
    if hlen > _MAX_HEADER_BYTES:
        raise HandoffError(f"header length {hlen} exceeds sanity cap")
    off = len(MAGIC) + 4
    if len(blob) < off + hlen:
        raise HandoffError(f"truncated header: need {hlen} bytes, "
                           f"have {len(blob) - off}")
    try:
        header = json.loads(blob[off:off + hlen])
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise HandoffError(f"unparseable header: {e}") from e
    off += hlen
    if not isinstance(header, dict):
        raise HandoffError("header is not an object")
    version = header.get("version")
    if version != VERSION:
        raise HandoffError(f"version {version!r} not supported "
                           f"(this build speaks {VERSION})")
    page_tokens = header.get("page_tokens")
    n_pages = header.get("n_pages")
    tokens = header.get("tokens")
    metas = header.get("sections")
    if not (isinstance(page_tokens, int) and page_tokens >= 1
            and isinstance(n_pages, int) and n_pages >= 1
            and isinstance(tokens, list) and isinstance(metas, list)
            and metas):
        raise HandoffError("header missing page_tokens/n_pages/tokens/"
                           "sections")
    if len(tokens) != n_pages * page_tokens:
        raise HandoffError(f"header token count {len(tokens)} != "
                           f"{n_pages} pages * {page_tokens} tokens")
    if expect_page_tokens is not None and page_tokens != expect_page_tokens:
        raise HandoffError(
            f"page-size mismatch: blob paged at {page_tokens} tokens, "
            f"this arena at {expect_page_tokens}")
    if expect_model is not None \
            and header.get("model", "") != expect_model:
        raise HandoffError(
            f"model mismatch: blob holds KV from "
            f"{header.get('model', '')!r}, this replica serves "
            f"{expect_model!r}")
    if expect_sections is not None:
        got = {m.get("name") for m in metas if isinstance(m, dict)}
        want = set(expect_sections)
        if got != want:
            raise HandoffError(f"section-set mismatch: blob has "
                               f"{sorted(got)}, arena needs {sorted(want)}")
    sections: dict[str, np.ndarray] = {}
    for meta in metas:
        if not isinstance(meta, dict):
            raise HandoffError("malformed section meta")
        name, dtype_name = meta.get("name"), meta.get("dtype")
        shape, nbytes = meta.get("shape"), meta.get("bytes")
        if not (isinstance(name, str) and isinstance(dtype_name, str)
                and isinstance(shape, list) and isinstance(nbytes, int)):
            raise HandoffError(f"malformed section meta: {meta}")
        try:
            dt = _dtype(dtype_name)
        except TypeError as e:
            raise HandoffError(f"section {name!r}: unknown dtype "
                               f"{dtype_name!r}") from e
        shape = tuple(int(s) for s in shape)
        if len(shape) < 3 or shape[1] != n_pages or shape[2] != page_tokens:
            raise HandoffError(f"section {name!r} shape {shape} is not "
                               f"(L, {n_pages}, {page_tokens}, ...)")
        want_bytes = int(np.prod(shape)) * dt.itemsize
        if nbytes != want_bytes:
            raise HandoffError(f"section {name!r}: declared {nbytes} bytes "
                               f"but shape/dtype imply {want_bytes}")
        if len(blob) < off + nbytes:
            raise HandoffError(
                f"truncated stream: section {name!r} needs {nbytes} bytes, "
                f"{len(blob) - off} remain")
        if expect_sections is not None:
            exp_dtype, exp_tail = expect_sections[name]
            if dt != _dtype(exp_dtype):
                raise HandoffError(
                    f"dtype mismatch on {name!r}: blob {dt.name}, "
                    f"arena {_dtype(exp_dtype).name}")
            if tuple(exp_tail) != shape[3:]:
                raise HandoffError(
                    f"section {name!r} trailing shape {shape[3:]} != "
                    f"arena's {tuple(exp_tail)}")
        sections[name] = np.frombuffer(
            blob, dtype=dt, count=int(np.prod(shape)),
            offset=off).reshape(shape)
        off += nbytes
    if off != len(blob):
        raise HandoffError(f"{len(blob) - off} trailing bytes after the "
                           "declared sections")
    return header, sections


def check_device_sections(tokens: list, sections: dict, *,
                          expect_page_tokens: int,
                          expect_sections: Optional[dict] = None,
                          expect_model: Optional[str] = None,
                          model: str = "",
                          allow_padded: bool = False
                          ) -> tuple[int, dict, int]:
    """The deserialization-side contract (``deserialize_pages``'
    model/section-set/dtype/trailing-shape/page-geometry checks) applied
    directly to LIVE arrays — ONE definition for every device-path door
    (the stream assembler's per-fragment check and the engine's
    monolithic adopt), so the wire and device contracts cannot drift.
    Duck-typed on ``.dtype``/``.shape``: device buffers never touch
    numpy. ``allow_padded`` accepts pow2-padded runs (export_run) and
    returns them trimmed to the true page count (a device-side slice —
    how the padding dies without a host copy); exact-width callers get
    their sections back untouched. Returns (n_pages, sections, nbytes);
    raises HandoffError on any mismatch."""
    t = expect_page_tokens
    if not tokens or len(tokens) % t:
        raise HandoffError(
            f"device run token count {len(tokens)} is not a multiple of "
            f"page_tokens {t}")
    n = len(tokens) // t
    if expect_model is not None and model != expect_model:
        raise HandoffError(
            f"model mismatch: device run holds KV from {model!r}, "
            f"this replica serves {expect_model!r}")
    if expect_sections is not None:
        got, want = set(sections), set(expect_sections)
        if got != want:
            raise HandoffError(
                f"section-set mismatch: device run has {sorted(got)}, "
                f"arena needs {sorted(want)}")
    nbytes = 0
    out = {}
    for name, a in sections.items():
        page_ok = a.shape[1] >= n if allow_padded else a.shape[1] == n
        if a.ndim < 3 or not page_ok or a.shape[2] != t:
            raise HandoffError(
                f"device section {name!r} shape {tuple(a.shape)} is not "
                f"(L, {n}, {t}, ...)")
        if expect_sections is not None:
            exp_dtype, exp_tail = expect_sections[name]
            if str(a.dtype) != exp_dtype \
                    and str(a.dtype) != _dtype(exp_dtype).name:
                raise HandoffError(
                    f"dtype mismatch on {name!r}: device run {a.dtype}, "
                    f"arena {exp_dtype}")
            if tuple(exp_tail) != tuple(a.shape[3:]):
                raise HandoffError(
                    f"device section {name!r} trailing shape "
                    f"{tuple(a.shape[3:])} != arena's {tuple(exp_tail)}")
        out[name] = a[:, :n] if a.shape[1] != n else a
        nbytes += int(out[name].size) * int(out[name].dtype.itemsize)
    return n, out, nbytes


# -- streaming chunk frames (ISSUE 10) ----------------------------------------

def serialize_chunk_frame(stream_id: str, seq: int, payload: bytes, *,
                          final: bool = False,
                          total_tokens: Optional[int] = None) -> bytes:
    """One stream frame: CHUNK_MAGIC | u32 header_len | header JSON |
    payload. ``payload`` is a ``serialize_pages`` blob for this chunk's
    completed pages (empty on a pure close frame). The FINAL frame
    carries ``total_tokens`` — the token count the whole stream claims —
    so a receiver can detect a torn stream even when every individual
    frame parsed (all-or-nothing adoption needs a stream-level length
    check, not just per-frame ones)."""
    if not stream_id:
        raise HandoffError("empty stream id")
    if seq < 0:
        raise HandoffError(f"negative seq {seq}")
    if final and total_tokens is None:
        raise HandoffError("final frame needs total_tokens")
    header = {"version": CHUNK_VERSION, "stream": str(stream_id),
              "seq": int(seq), "final": bool(final),
              "payload_bytes": len(payload)}
    if total_tokens is not None:
        header["total_tokens"] = int(total_tokens)
    raw = json.dumps(header).encode()
    return b"".join([CHUNK_MAGIC, len(raw).to_bytes(4, "big"), raw, payload])


def parse_chunk_frame(blob: bytes) -> tuple[dict, bytes]:
    """(header, payload bytes) of one chunk frame; every malformation —
    truncation, bad magic, foreign version, length drift, trailing
    garbage — raises HandoffError (the assembler then drops the whole
    stream: a stream that ever carried a bad frame must not adopt)."""
    if len(blob) < len(CHUNK_MAGIC) + 4:
        raise HandoffError(f"truncated chunk frame: {len(blob)} bytes")
    if blob[:len(CHUNK_MAGIC)] != CHUNK_MAGIC:
        raise HandoffError("bad magic: not a KV chunk frame")
    hlen = int.from_bytes(blob[len(CHUNK_MAGIC):len(CHUNK_MAGIC) + 4], "big")
    if hlen > _MAX_HEADER_BYTES:
        raise HandoffError(f"chunk header length {hlen} exceeds sanity cap")
    off = len(CHUNK_MAGIC) + 4
    if len(blob) < off + hlen:
        raise HandoffError(f"truncated chunk header: need {hlen} bytes, "
                           f"have {len(blob) - off}")
    try:
        header = json.loads(blob[off:off + hlen])
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise HandoffError(f"unparseable chunk header: {e}") from e
    off += hlen
    if not isinstance(header, dict):
        raise HandoffError("chunk header is not an object")
    if header.get("version") != CHUNK_VERSION:
        raise HandoffError(f"chunk version {header.get('version')!r} not "
                           f"supported (this build speaks {CHUNK_VERSION})")
    stream, seq = header.get("stream"), header.get("seq")
    nbytes = header.get("payload_bytes")
    if not (isinstance(stream, str) and stream and isinstance(seq, int)
            and seq >= 0 and isinstance(nbytes, int) and nbytes >= 0):
        raise HandoffError("chunk header missing stream/seq/payload_bytes")
    if len(blob) - off != nbytes:
        raise HandoffError(
            f"torn chunk frame: payload declares {nbytes} bytes, "
            f"{len(blob) - off} present")
    return header, blob[off:]


def merge_section_frames(done: dict) -> dict[str, np.ndarray]:
    """One {name: (L, n, T, ...)} dict from a closed stream's per-frame
    section dicts (``_close`` hands frames back unmerged so the adoption
    hot path chooses WHERE the concat runs). This is the HOST merge for
    wire-tier consumers; device adopters concatenate device-side instead
    (the serving engine's _merged_stream_sections)."""
    frames = done["section_frames"]
    if len(frames) == 1:
        return dict(frames[0])
    return {name: np.concatenate([f[name] for f in frames], axis=1)
            for name in frames[0]}


class _StreamState:
    __slots__ = ("next_seq", "tokens", "sections", "nbytes", "last_seen")

    def __init__(self, now: float):
        self.next_seq = 0
        self.tokens: list = []
        self.sections: list[dict] = []     # per-frame {name: (L,n,T,...)}
        self.nbytes = 0
        self.last_seen = now


class HandoffStreamAssembler:
    """Strict-order chunk-stream assembly on the decode side: frames are
    buffered HOST-side per stream and the arena is touched only when the
    FINAL frame lands and the whole stream checks out — all-or-nothing
    page accounting by construction (a torn/duplicate/reordered/stale
    stream leaves both arenas exactly as they were).

    Two entry points share ONE seq/TTL state machine: ``feed`` takes wire
    chunk frames (parse + deserialize, the HTTP path), ``feed_fragment``
    takes already-materialized section arrays (the DEVICE transfer path,
    ISSUE 11 — same ordering/TTL/total_tokens discipline, just no
    serialize/deserialize in the middle; fragments buffer as device
    arrays and never touch numpy). A stream id is one stream regardless
    of which door its frames came through — a sender that mixed paths
    mid-stream still gets strict-seq treatment.

    Rejection surface (each raises HandoffError and DROPS the stream —
    once a stream carried one bad frame nothing later may resurrect it):
    out-of-order or duplicate ``seq``; a frame for an unknown stream not
    starting at seq 0 (stale sender, or the stream was already dropped);
    per-frame payload validation (deserialize_pages with the adopting
    arena's expectations, or the same geometry checks applied directly to
    device fragments); a final ``total_tokens`` that disagrees with
    what actually arrived; idle streams past ``ttl_s`` (GC'd on every
    feed — an abandoned sender must not pin host memory forever, and a
    final frame arriving AFTER its stream expired is stale, not a
    resurrection).

    Not thread-safe: the engine serializes ``feed``/``feed_fragment``
    under its handoff lock. ``clock`` is injectable (tests drive the TTL
    deterministically)."""

    def __init__(self, *, expect_page_tokens: int,
                 expect_sections: Optional[dict] = None,
                 expect_model: Optional[str] = None,
                 max_streams: int = 8, ttl_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        self.expect_page_tokens = expect_page_tokens
        self.expect_sections = expect_sections
        self.expect_model = expect_model
        self.max_streams = max_streams
        self.ttl_s = ttl_s
        self.clock = clock
        self._streams: dict[str, _StreamState] = {}

    def __len__(self) -> int:
        return len(self._streams)

    def _gc(self, now: float) -> int:
        dead = [sid for sid, st in self._streams.items()
                if now - st.last_seen > self.ttl_s]
        for sid in dead:
            del self._streams[sid]
        return len(dead)

    def _advance(self, sid: str, seq: int, now: float) -> _StreamState:
        """The shared seq/TTL state machine: open-at-0, strict order,
        bounded stream count. Raises HandoffError (dropping the stream on
        an order violation) — both feed doors go through here."""
        st = self._streams.get(sid)
        if st is None:
            if seq != 0:
                raise HandoffError(
                    f"stale stream {sid!r}: frame seq {seq} for a stream "
                    "this side never opened (expired, dropped, or the "
                    "open frame was lost)")
            if len(self._streams) >= self.max_streams:
                raise HandoffError(
                    f"too many open handoff streams ({self.max_streams})")
            st = self._streams[sid] = _StreamState(now)
        if seq != st.next_seq:
            del self._streams[sid]
            kind = "duplicate" if seq < st.next_seq else "reordered/lost"
            raise HandoffError(
                f"stream {sid!r}: {kind} frame (got seq {seq}, expected "
                f"{st.next_seq}) — stream dropped, nothing adopted")
        st.last_seen = now
        st.next_seq += 1
        return st

    def _close(self, sid: str, st: _StreamState, seq: int,
               total_tokens) -> dict:
        """Final-frame checks + result assembly. The payload comes back
        as ``section_frames`` — the per-frame dicts, NOT concatenated:
        the adopter merges them itself (``merge_section_frames`` below,
        device-side for device fragments), so the close never pays a
        host-side copy of the whole run on the adoption hot path."""
        if total_tokens != len(st.tokens):
            self._streams.pop(sid, None)
            raise HandoffError(
                f"torn stream {sid!r}: final frame claims {total_tokens} "
                f"tokens, {len(st.tokens)} arrived")
        if not st.tokens:
            self._streams.pop(sid, None)
            raise HandoffError(f"stream {sid!r} closed with no pages")
        frames = st.next_seq
        del self._streams[sid]
        return {"final": True, "seq": seq, "tokens": list(st.tokens),
                "bytes": st.nbytes, "frames": frames,
                "section_frames": list(st.sections)}

    def feed(self, blob: bytes) -> dict:
        """One WIRE frame in. Returns {"final": False, "seq"} while the
        stream is still open, or — on a valid final frame — {"final":
        True, "seq", "tokens", "section_frames", "bytes", "frames"}
        ready for arena adoption (merge the frames with
        ``merge_section_frames`` or device-side). Raises HandoffError
        (stream dropped) on any rejection."""
        now = self.clock()
        self._gc(now)
        header, payload = parse_chunk_frame(blob)
        sid, seq = header["stream"], header["seq"]
        st = self._advance(sid, seq, now)
        try:
            if payload:
                hdr, sections = deserialize_pages(
                    payload, expect_page_tokens=self.expect_page_tokens,
                    expect_sections=self.expect_sections,
                    expect_model=self.expect_model)
                st.tokens.extend(hdr["tokens"])
                st.sections.append(sections)
            st.nbytes += len(blob)
        except HandoffError:
            self._streams.pop(sid, None)
            raise
        if not header.get("final"):
            return {"final": False, "seq": seq}
        return self._close(sid, st, seq, header.get("total_tokens"))

    def feed_fragment(self, stream_id: str, seq: int, tokens: list,
                      sections: dict, *, final: bool = False,
                      total_tokens=None, model: str = "") -> dict:
        """One DEVICE fragment in — the zero-serialization door (ISSUE
        11): ``sections[name]`` is an (L, n, T, ...) device (or host)
        array for this fragment's pages, already trimmed to its true page
        count. Same state machine, TTL and all-or-nothing close as
        ``feed``; the final result carries ``section_frames`` (per-frame
        dicts, NOT concatenated — the adopter concatenates device-side).
        A pure close fragment passes empty tokens/sections and
        ``final=True`` with ``total_tokens``."""
        if not stream_id:
            raise HandoffError("empty stream id")
        if final and total_tokens is None:
            raise HandoffError("final fragment needs total_tokens")
        now = self.clock()
        self._gc(now)
        st = self._advance(str(stream_id), int(seq), now)
        sid = str(stream_id)
        try:
            if tokens or sections:
                _, checked, nbytes = check_device_sections(
                    list(tokens), sections,
                    expect_page_tokens=self.expect_page_tokens,
                    expect_sections=self.expect_sections,
                    expect_model=self.expect_model, model=model)
                st.nbytes += nbytes
                st.tokens.extend(int(tk) for tk in tokens)
                st.sections.append(checked)
        except HandoffError:
            self._streams.pop(sid, None)
            raise
        if not final:
            return {"final": False, "seq": seq}
        return self._close(sid, st, seq, total_tokens)
