"""Fleet tier: replica-aware request routing + SLO-driven autoscaling.

One serving replica (ServingEngine + serve_main) saturates at its slot
count; the ROADMAP north star ("heavy traffic from millions of users")
needs a tier ABOVE replicas. This package is that tier:

- ``registry``  — replicas register/heartbeat with live load stats; stale
  or probe-failing replicas are evicted (the router never routes blind).
- ``router``    — an HTTP front door speaking the same ``/v1/*`` +
  ``/generate`` API as serve_main: least-loaded routing with
  prefix-affinity, streaming passthrough, per-replica circuit breakers
  with retry-on-next-replica, 429 + Retry-After when the whole fleet is
  saturated, and W3C traceparent propagation so a request's router span
  parents its engine span tree.
- ``autoscaler`` — an injected-clock control loop sizing the replica set
  from queue depth + TTFT-SLO burn (hysteresis + cooldowns), creating
  serving pods against the virtual node and drain-before-delete on the
  way down so no request is dropped.
- ``scheduler``  — heterogeneity- and goodput-aware placement over mixed
  TPU generations (ISSUE 19): declared node pools, a live effective-
  throughput matrix refined from fleet telemetry, goodput-per-dollar
  placement, best-effort packing with lowest-goodput-loss-first
  preemption.

Entry point: ``python -m k8s_runpod_kubelet_tpu.fleet.router_main``.
"""

from .autoscaler import AutoscalerConfig, FleetAutoscaler, KubePodScaler
from .registry import (DRAINING, READY, Replica, ReplicaRegistry,
                       ReplicaReporter, ReplicaStats)
from .router import FleetRouter, RouterConfig, serve_router
from .scheduler import (FleetScheduler, NodePool, Placement,
                        PoolSpecError, ThroughputMatrix, parse_pools)

__all__ = [
    "AutoscalerConfig", "FleetAutoscaler", "KubePodScaler",
    "READY", "DRAINING", "Replica", "ReplicaRegistry", "ReplicaReporter",
    "ReplicaStats", "FleetRouter", "RouterConfig", "serve_router",
    "FleetScheduler", "NodePool", "Placement", "PoolSpecError",
    "ThroughputMatrix", "parse_pools",
]
