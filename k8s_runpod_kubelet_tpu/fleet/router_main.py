"""Fleet router entrypoint: registry + front door (+ optional autoscaler).

Run: python -m k8s_runpod_kubelet_tpu.fleet.router_main \
        --port 8090 --min-replicas 1 --max-replicas 4

Replicas point at it with ``serve_main --fleet-router http://router:8090
--fleet-advertise http://$(POD_IP):8000`` and self-register; the router
then load-balances ``/v1/*`` + ``/generate`` across them. With
``--autoscale`` (and kube credentials) the SLO control loop creates and
drains serving pods against the virtual TPU node.

Every knob is also a config field (fleet_* in config.py) with the
TPU_FLEET_* env vars, same precedence as the kubelet: flags > env > file
> defaults.
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
import sys
import threading

from .. import config as config_mod
from ..metrics import Metrics
from ..tracing import Tracer
from .autoscaler import AutoscalerConfig, FleetAutoscaler, KubePodScaler
from .registry import ReplicaRegistry
from .router import FleetRouter, RouterConfig, serve_router

log = logging.getLogger("fleet-router")


def parse_flags(argv):
    p = argparse.ArgumentParser("tpu-fleet-router")
    p.add_argument("--port", dest="fleet_router_port", type=int, default=None)
    p.add_argument("--heartbeat-interval", dest="fleet_heartbeat_interval_s",
                   type=float, default=None,
                   help="how often replicas heartbeat (informs the timeout)")
    p.add_argument("--heartbeat-timeout", dest="fleet_heartbeat_timeout_s",
                   type=float, default=None,
                   help="heartbeats older than this mark a replica suspect "
                        "(probed, then evicted)")
    p.add_argument("--ttft-slo", dest="fleet_ttft_slo_s", type=float,
                   default=None, help="scale up when any replica's recent "
                                      "TTFT p95 exceeds this many seconds")
    p.add_argument("--target-queue-per-replica",
                   dest="fleet_target_queue_per_replica", type=float,
                   default=None)
    p.add_argument("--min-replicas", dest="fleet_min_replicas", type=int,
                   default=None)
    p.add_argument("--max-replicas", dest="fleet_max_replicas", type=int,
                   default=None)
    # disaggregated pools (ISSUE 9): configuring a prefill AND a decode
    # pool (max > 0) switches the autoscaler to per-pool control loops —
    # prefill scales on TTFT burn + queue depth, decode on ITL p95 +
    # free KV pages — and the router two-hops generation requests
    p.add_argument("--prefill-min-replicas",
                   dest="fleet_prefill_min_replicas", type=int, default=None)
    p.add_argument("--prefill-max-replicas",
                   dest="fleet_prefill_max_replicas", type=int, default=None,
                   help="prefill pool ceiling (0 = pool disabled)")
    p.add_argument("--decode-min-replicas",
                   dest="fleet_decode_min_replicas", type=int, default=None)
    p.add_argument("--decode-max-replicas",
                   dest="fleet_decode_max_replicas", type=int, default=None,
                   help="decode pool ceiling (0 = pool disabled)")
    p.add_argument("--itl-slo", dest="fleet_itl_slo_s", type=float,
                   default=None,
                   help="decode pool scale-up signal: any decode replica's "
                        "recent inter-token p95 over this many seconds")
    p.add_argument("--min-free-kv-page-frac",
                   dest="fleet_min_free_kv_page_frac", type=float,
                   default=None,
                   help="decode pool scale-up signal: pool-wide free KV "
                        "page fraction under this floor")
    p.add_argument("--handoff-timeout",
                   dest="fleet_handoff_timeout_s", type=float, default=None,
                   help="budget for the prefill hop (compute + page push); "
                        "past it the router falls back to single-hop")
    p.add_argument("--device-transfer", default=None, choices=["on", "off"],
                   dest="fleet_device_transfer_enabled",
                   help="annotate same-placement-domain two-hop routes for "
                        "device-native KV handoff (arena-to-arena, zero "
                        "host copies); off = every hop rides the wire "
                        "codec")
    p.add_argument("--prefix-directory", default=None, choices=["on", "off"],
                   dest="fleet_prefix_directory_enabled",
                   help="run the fleet-wide KV prefix directory (ISSUE 16): "
                        "replicas publish their cached prefix keys via "
                        "heartbeats and the router plans PULL hops — a "
                        "cold replica fetches matched pages from the "
                        "owning replica instead of re-prefilling; off = "
                        "routing only, no directory")
    p.add_argument("--pull-timeout", dest="fleet_pull_timeout_s",
                   type=float, default=None,
                   help="budget for one directory-pull hop (owner export "
                        "+ transfer + adoption); past it the request "
                        "just re-prefills")
    p.add_argument("--prefix-broadcast", default=None, choices=["on", "off"],
                   dest="fleet_prefix_broadcast",
                   help="restore the pre-directory POST /prefix fan-out "
                        "(register the prefix on EVERY ready replica up "
                        "front) instead of register-once + lazy pulls")
    p.add_argument("--directory-capacity", dest="fleet_directory_capacity",
                   type=int, default=None,
                   help="prefix-directory LRU size: entries held before "
                        "the least-recently-touched claim evicts "
                        "(default 4096)")
    p.add_argument("--pools", dest="fleet_pools", default=None,
                   help="heterogeneous node pools as [name=]generation:"
                        "chips, comma-separated (e.g. v5e:32,v5p:64); "
                        "non-empty routes every scale-up through the "
                        "goodput-per-dollar fleet scheduler")
    p.add_argument("--slo-short-window", dest="fleet_slo_short_window_s",
                   type=float, default=None,
                   help="SLO burn-rate short window in seconds (fast "
                        "detection; default 300)")
    p.add_argument("--slo-long-window", dest="fleet_slo_long_window_s",
                   type=float, default=None,
                   help="SLO burn-rate long window in seconds (sustained "
                        "evidence; default 3600)")
    p.add_argument("--slo-burn-threshold", dest="fleet_slo_burn_threshold",
                   type=float, default=None,
                   help="a signal burns when BOTH windows consume error "
                        "budget this many times faster than sustainable")
    p.add_argument("--slo-budget-frac", dest="fleet_slo_budget_frac",
                   type=float, default=None,
                   help="error budget: fraction of time each SLO may be "
                        "breached (default 0.05)")
    p.add_argument("--slo-error-rate", dest="fleet_slo_error_rate",
                   type=float, default=None,
                   help="request error-ratio objective for the error_rate "
                        "burn signal (default 0.01)")
    p.add_argument("--scale-up-cooldown", dest="fleet_scale_up_cooldown_s",
                   type=float, default=None)
    p.add_argument("--scale-down-cooldown",
                   dest="fleet_scale_down_cooldown_s", type=float,
                   default=None)
    p.add_argument("--autoscale", action="store_true",
                   help="run the SLO autoscaler (needs kube credentials); "
                        "off = routing + registry only")
    p.add_argument("--node-name", dest="node_name", default=None,
                   help="virtual node serving pods are created on")
    p.add_argument("--namespace", default=None)
    p.add_argument("--kubeconfig", default=None)
    p.add_argument("--serving-image", default="",
                   help="image for autoscaler-created serving pods")
    p.add_argument("--serving-chips", type=int, default=8,
                   help="google.com/tpu chips each serving pod requests")
    p.add_argument("--provider-config", dest="provider_config", default=None)
    p.add_argument("--trace-export", dest="trace_export_path", default=None,
                   help="append fleet.route/fleet.scale spans to this JSONL "
                        "(render with tools/fleet_summary.py)")
    p.add_argument("--log-level", dest="log_level", default=None)
    return p.parse_args(argv)


def build(cfg: config_mod.Config, kube=None, autoscale: bool = False,
          serving_image: str = "", serving_chips: int = 8):
    """Wire registry + router (+ autoscalers); injectable kube for tests.

    Returns (registry, router, autoscalers): an empty list without
    --autoscale, ONE whole-fleet loop in the single-pool default, or one
    loop PER POOL (prefill + decode, each with its role's signals and its
    own pod scaler/reaper) when both disaggregated pools are configured
    (fleet_prefill_max_replicas > 0 and fleet_decode_max_replicas > 0)."""
    metrics = Metrics()
    tracer = Tracer(max_spans=cfg.trace_ring_size,
                    export_path=cfg.trace_export_path)
    directory = None
    if cfg.fleet_prefix_directory_enabled:
        from .prefix_directory import PrefixDirectory
        directory = PrefixDirectory(metrics=metrics,
                                    max_entries=cfg.fleet_directory_capacity)
    # heterogeneous node pools (ISSUE 19): a declared fleet_pools spec
    # stands up the goodput-per-dollar scheduler — heartbeats refine its
    # throughput matrix via the registry, the autoscalers request
    # capacity through it, /debug/scheduler exposes it
    scheduler = None
    if cfg.fleet_pools:
        from .scheduler import FleetScheduler
        scheduler = FleetScheduler(cfg.fleet_pools, metrics=metrics,
                                   tracer=tracer,
                                   default_serving_chips=serving_chips)
    # SLO burn-rate layer (ISSUE 17): fed by every accepted heartbeat,
    # read by GET /debug/slo and the autoscalers' latency corroboration
    from .slo import SLOTracker
    slo = SLOTracker(
        ttft_slo_s=cfg.fleet_ttft_slo_s,
        itl_slo_s=cfg.fleet_itl_slo_s,
        error_rate_slo=cfg.fleet_slo_error_rate,
        short_window_s=cfg.fleet_slo_short_window_s,
        long_window_s=cfg.fleet_slo_long_window_s,
        burn_threshold=cfg.fleet_slo_burn_threshold,
        budget_frac=cfg.fleet_slo_budget_frac,
        metrics=metrics, tracer=tracer)
    # cost attribution plane (ISSUE 20): heartbeat metric snapshots merge
    # into /metrics/fleet, cost snapshots roll up into /debug/costs
    from ..metrics import MetricsAggregator
    from .registry import FleetCostLedger
    aggregator = MetricsAggregator()
    cost_ledger = FleetCostLedger()
    registry = ReplicaRegistry(
        metrics=metrics, tracer=tracer,
        heartbeat_timeout_s=cfg.fleet_heartbeat_timeout_s,
        breaker_failure_threshold=cfg.breaker_failure_threshold,
        breaker_reset_s=cfg.breaker_reset_s,
        directory=directory, slo=slo, scheduler=scheduler,
        aggregator=aggregator, cost_ledger=cost_ledger)
    router = FleetRouter(
        registry,
        RouterConfig(port=cfg.fleet_router_port,
                     handoff_timeout_s=cfg.fleet_handoff_timeout_s,
                     device_transfer_enabled=(
                         cfg.fleet_device_transfer_enabled),
                     prefix_directory_enabled=(
                         cfg.fleet_prefix_directory_enabled),
                     pull_timeout_s=cfg.fleet_pull_timeout_s,
                     prefix_broadcast=cfg.fleet_prefix_broadcast,
                     kv_page_tokens=cfg.kv_page_tokens),
        metrics=metrics, tracer=tracer, directory=directory, slo=slo,
        scheduler=scheduler)
    autoscalers = []
    if autoscale:
        from ..kube import RealKubeClient
        kube = kube or RealKubeClient.from_env(cfg.kubeconfig)
        disagg = (cfg.fleet_prefill_max_replicas > 0
                  and cfg.fleet_decode_max_replicas > 0)
        base = dict(
            target_queue_per_replica=cfg.fleet_target_queue_per_replica,
            ttft_slo_s=cfg.fleet_ttft_slo_s,
            scale_up_cooldown_s=cfg.fleet_scale_up_cooldown_s,
            scale_down_cooldown_s=cfg.fleet_scale_down_cooldown_s)
        if disagg:
            pools = [
                ("prefill", cfg.fleet_prefill_min_replicas,
                 cfg.fleet_prefill_max_replicas, {}),
                ("decode", cfg.fleet_decode_min_replicas,
                 cfg.fleet_decode_max_replicas,
                 {"itl_slo_s": cfg.fleet_itl_slo_s,
                  "min_free_kv_page_frac":
                      cfg.fleet_min_free_kv_page_frac}),
            ]
        else:
            pools = [("", cfg.fleet_min_replicas,
                      cfg.fleet_max_replicas, {})]
        for role, mn, mx, extra in pools:
            scaler = KubePodScaler(kube, cfg.node_name, cfg.namespace,
                                   chips=serving_chips, image=serving_image,
                                   role=role)
            autoscalers.append(FleetAutoscaler(
                registry, scaler,
                AutoscalerConfig(min_replicas=mn, max_replicas=mx,
                                 role=role, **base, **extra),
                metrics=metrics, tracer=tracer, slo=slo,
                scheduler=scheduler))
    return registry, router, autoscalers


def main(argv=None) -> int:
    args = parse_flags(argv if argv is not None else sys.argv[1:])
    for onoff in ("fleet_device_transfer_enabled",
                  "fleet_prefix_directory_enabled",
                  "fleet_prefix_broadcast"):
        # choices are "on"/"off"; config's bool coercion only knows
        # true/false/1/yes spellings
        if getattr(args, onoff) is not None:
            setattr(args, onoff, getattr(args, onoff) == "on")
    known = {f.name for f in dataclasses.fields(config_mod.Config)}
    overrides = {k: v for k, v in vars(args).items()
                 if v is not None and k in known}
    cfg = config_mod.load(file_path=args.provider_config, overrides=overrides)
    logging.basicConfig(level=getattr(logging, cfg.log_level.upper(),
                                      logging.INFO))
    registry, router, autoscalers = build(
        cfg, autoscale=args.autoscale, serving_image=args.serving_image,
        serving_chips=args.serving_chips)
    httpd = serve_router(router)
    log.info("fleet router on :%d (/v1/*, /generate, /fleet/*, /metrics, "
             "/metrics/fleet, /debug/fleet, /debug/costs)",
             httpd.server_address[1])

    stop = threading.Event()
    # eviction sweep at the heartbeat cadence: a dead replica is suspect
    # after one missed timeout window, gone after its failed probe
    def sweep_loop():
        while not stop.is_set():
            try:
                registry.sweep()
            except Exception:  # noqa: BLE001 — the sweep must survive bad probes
                log.exception("registry sweep failed")
            stop.wait(cfg.fleet_heartbeat_interval_s)

    threading.Thread(target=sweep_loop, name="fleet-sweep",
                     daemon=True).start()
    for autoscaler in autoscalers:
        autoscaler.run(interval_s=cfg.fleet_heartbeat_interval_s)
        ac = autoscaler.cfg
        log.info("autoscaler[%s] on: %d..%d replicas, queue target %.1f, "
                 "TTFT SLO %.2fs, ITL SLO %.3fs",
                 ac.role or "fleet", ac.min_replicas, ac.max_replicas,
                 ac.target_queue_per_replica, ac.ttft_slo_s, ac.itl_slo_s)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    stop.set()
    for autoscaler in autoscalers:
        autoscaler.stop()
    httpd.shutdown()
    router.tracer.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
