"""SLO-driven fleet autoscaler: size the replica set to the traffic.

An injected-clock control loop (``tick()``; router_main wraps it in a
timer thread) that computes the desired replica count from the registry's
live load view:

- **scale up** when sustained queue depth per replica exceeds the target
  OR the fleet's worst recent TTFT p95 burns the SLO — after
  ``scale_up_stable_s`` of sustained overload and outside the up-cooldown
  (hysteresis: one spiky scrape must not buy a TPU slice);
- **scale down** when the fleet is sustained-idle (no queue, utilization
  under the floor) — but ONLY via drain-first: the victim gets ``POST
  /drain`` (stop admitting, finish in-flight, deregister), and its pod is
  deleted only once the drain completes (or times out). No request is
  ever dropped by a scale-down.

Scale-up creates real serving pods against the virtual node through the
existing kube client — the pod rides the whole QueuedResources
provisioning path (deploy -> provisioning -> gang launch -> ready), which
is exactly what the fleet soak exercises end to end.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os
import threading
import time
from typing import Callable, Optional

from ..cloud.transport import CircuitOpenError, TransportError
from .registry import DECODE, DRAINING, PREFILL, Replica, ReplicaRegistry

log = logging.getLogger(__name__)

# Serving knobs the autoscaler forwards from its own environment into
# every serving pod it creates (the helm chart sets them on the router
# deployment; serve_main reads them via config._ENV_MAP) — the wiring
# path for the paged-KV prefix cache (ISSUE 8) at fleet scale.
SERVING_PASSTHROUGH_ENV = ("TPU_KV_PAGE_TOKENS", "TPU_KV_POOL_PAGES",
                           "TPU_PREFIX_CACHE_ENABLED",
                           "TPU_KV_PAGED_DECODE",
                           "TPU_KV_PAGED_PREFILL",
                           "TPU_KV_ARENA_SHARDING",
                           "TPU_SERVING_CHUNK_TOKENS",
                           "TPU_HANDOFF_STREAM_WINDOW",
                           "TPU_FLEET_DEVICE_TRANSFER_ENABLED",
                           "TPU_FLEET_PLACEMENT_DOMAIN",
                           "TPU_FLEET_PREFIX_DIRECTORY_ENABLED",
                           "TPU_FLEET_PULL_TIMEOUT_S",
                           "TPU_FLEET_PLACEMENT_DOMAIN_MODE",
                           "TPU_SERVING_FLIGHT_RECORDER",
                           "TPU_SERVING_PROFILER_PORT",
                           "TPU_SERVING_PROFILE_CAPTURE",
                           "TPU_SERVING_COST_METER")


@dataclasses.dataclass
class AutoscalerConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    # scale-up signals: sustained queue depth per ready replica, or the
    # worst replica's recent TTFT p95 over the SLO
    target_queue_per_replica: float = 4.0
    ttft_slo_s: float = 2.0
    # disaggregated pools (ISSUE 9): ``role`` scopes this control loop to
    # one pool — it sizes, drains and reaps ONLY replicas/pods of that
    # role ("" = the whole fleet, the single-pool default). A decode-role
    # loop scales on its OWN signals: sustained ITL p95 over itl_slo_s
    # (decode is what disaggregation protects from prefill interference)
    # or free KV pages across the pool under min_free_kv_page_frac (page
    # exhaustion rejects admissions before slots fill). 0 disables a
    # signal.
    role: str = ""
    itl_slo_s: float = 0.0
    min_free_kv_page_frac: float = 0.0
    # hysteresis: how long a signal must hold before acting
    scale_up_stable_s: float = 10.0
    scale_down_stable_s: float = 60.0
    # cooldowns: minimum spacing between same-direction actions
    scale_up_cooldown_s: float = 30.0
    scale_down_cooldown_s: float = 120.0
    # scale-down eligibility: fleet-wide slot utilization under this floor
    scale_down_utilization: float = 0.25
    # a drain that outlives this is force-completed (pod deleted anyway —
    # the replica is presumed wedged; its breaker/eviction already stopped
    # new traffic)
    drain_timeout_s: float = 300.0
    # a created pod that never registers a replica within this window is
    # presumed failed and stops counting toward the fleet size
    boot_timeout_s: float = 900.0


class KubePodScaler:
    """Creates/deletes serving pods on the virtual TPU node via the
    existing kube client. ``on_create(pod)`` lets an embedding process
    (or the hermetic soak) hand the created pod straight to the
    provider, exactly as the pod controller would."""

    def __init__(self, kube, node_name: str, namespace: str = "default",
                 chips: int = 8, image: str = "",
                 template_fn: Optional[Callable[[str], dict]] = None,
                 on_create: Optional[Callable[[dict], None]] = None,
                 on_delete: Optional[Callable[[dict], None]] = None,
                 role: str = ""):
        # NB: when a FleetScheduler is wired (ISSUE 19) the autoscaler
        # calls create(name=..., placement=...) — the pod is born carrying
        # its reservation as tpu.dev/pool* annotations, so a restarted
        # scheduler can rebuild its table from live pods (adopt()).
        self.kube = kube
        self.node_name = node_name
        self.namespace = namespace
        self.chips = chips
        self.image = image or "gcr.io/tpu-fleet/serve:latest"
        self.template_fn = template_fn
        self.on_create = on_create
        # on_delete(pod) mirrors on_create: an embedding process hands the
        # deletion to the provider too, so the slice is released and
        # tombstoned exactly as if the pod controller saw the delete
        self.on_delete = on_delete
        # disaggregated pool (ISSUE 9): pods carry the role as a label
        # (so each pool's reaper sees only its own pods) and as
        # TPU_SERVING_ROLE env (so serve_main registers into the right
        # pool). "" = the legacy single-pool scaler.
        self.role = role
        self._seq = 0

    # pods carrying this label are FLEET-OWNED: the autoscaler may reap
    # one that no registered replica backs (a custom template_fn must
    # include it for orphan reaping to see its pods)
    FLEET_LABEL = "tpu.dev/fleet=serving"
    ROLE_LABEL = "tpu.dev/fleet-role"

    def _pod(self, name: str) -> dict:
        if self.template_fn is not None:
            return self._stamp_role(self.template_fn(name))
        container = {"name": "serve", "image": self.image,
                     "resources": {"limits": {
                         "google.com/tpu": str(self.chips)}}}
        env = [{"name": k, "value": os.environ[k]}
               for k in SERVING_PASSTHROUGH_ENV if os.environ.get(k)]
        if self.role:
            env.append({"name": "TPU_SERVING_ROLE", "value": self.role})
        if env:
            container["env"] = env
        labels = {"app": "tpu-serving", "tpu.dev/fleet": "serving"}
        if self.role:
            labels[self.ROLE_LABEL] = self.role
        return {"apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": name, "namespace": self.namespace,
                             "labels": labels},
                "spec": {"nodeName": self.node_name,
                         "containers": [container]}}

    def _stamp_role(self, pod: dict) -> dict:
        """Role-scope a custom template's pod: without the role label the
        pool's reaper never sees it, and without TPU_SERVING_ROLE it
        registers as `unified` — the pool loop would boot-timeout and
        recreate it forever. Stamped onto the template's output (unlike
        FLEET_LABEL, which templates must carry themselves, the role is
        the SCALER's identity, not the template's)."""
        if not self.role:
            return pod
        pod.setdefault("metadata", {}).setdefault("labels", {})[
            self.ROLE_LABEL] = self.role
        for container in pod.get("spec", {}).get("containers", []):
            env = container.setdefault("env", [])
            if not any(e.get("name") == "TPU_SERVING_ROLE" for e in env):
                env.append({"name": "TPU_SERVING_ROLE", "value": self.role})
        return pod

    def next_name(self) -> str:
        """Reserve the NEXT pod name without creating the pod — the
        scheduler-aware scale-up path places against the name first
        (place() is idempotent by tag), then creates, so a crash between
        the two leaves a reservation a retry reuses instead of a pod
        nothing accounted for."""
        self._seq += 1
        return (f"tpu-serving-{self.role}-{self._seq}" if self.role
                else f"tpu-serving-{self._seq}")

    def create(self, name: Optional[str] = None, placement=None) -> str:
        if name is None:
            name = self.next_name()
        pod = self._pod(name)
        if placement is not None:
            self._stamp_placement(pod, placement)
        created = self.kube.create_pod(pod)
        if self.on_create is not None:
            self.on_create(created)
        return name

    @staticmethod
    def _stamp_placement(pod: dict, placement):
        """Bake the scheduler's reservation into the pod: annotations are
        the durable record adopt() rebuilds from after a restart; the env
        vars let serve_main's reporter register with its generation/pool
        so heartbeats teach the right throughput-matrix cell; the
        generation annotation pins gang launch (translate.select_slice)
        to the pool's hardware."""
        from ..provider.annotations import Annotations as A
        anns = pod.setdefault("metadata", {}).setdefault("annotations", {})
        anns[A.POOL] = placement.pool
        anns[A.POOL_KIND] = placement.kind
        anns[A.GENERATION] = placement.generation
        if placement.best_effort:
            anns[A.BEST_EFFORT] = "true"
        for container in pod.get("spec", {}).get("containers", []):
            env = container.setdefault("env", [])
            env.append({"name": "TPU_SERVING_GENERATION",
                        "value": placement.generation})
            env.append({"name": "TPU_SERVING_POOL",
                        "value": placement.pool})

    def list_fleet_pods(self) -> list[str]:
        """Names of fleet-owned serving pods (by label) — the orphan
        reaper's ground truth of what exists in the cluster. A
        role-scoped scaler lists ONLY its pool's pods, so two pool
        reapers can never fight over (or reap) each other's pods."""
        return [p["metadata"]["name"]
                for p in self.list_fleet_pod_objects()]

    def list_fleet_pod_objects(self) -> list[dict]:
        """Full fleet-owned pod objects — FleetScheduler.adopt() rebuilds
        reservations from their tpu.dev/pool annotations on restart."""
        selector = self.FLEET_LABEL
        if self.role:
            selector += f",{self.ROLE_LABEL}={self.role}"
        return self.kube.list_pods(self.namespace,
                                   label_selector=selector)

    def delete(self, pod_name: str):
        pod = None
        if self.on_delete is not None:
            try:
                pod = self.kube.get_pod(self.namespace, pod_name)
            except Exception as e:  # noqa: BLE001 — already gone is fine
                log.info("fleet: pod %s gone before delete (%s)",
                         pod_name, e)
                pod = None
        # grace 0: the autoscaler only deletes AFTER the drain emptied the
        # engine (or timed out), so there is nothing left for a graceful
        # termination period to protect
        self.kube.delete_pod(self.namespace, pod_name, grace_period_s=0)
        if pod is not None:
            self.on_delete(pod)


@dataclasses.dataclass
class _Drain:
    replica_id: str
    pod_name: str
    started_at: float


class FleetAutoscaler:
    """The control loop. All timing flows through the injected ``clock``;
    ``tick()`` is side-effect-idempotent between signal changes (calling
    it twice in one instant acts at most once)."""

    def __init__(self, registry: ReplicaRegistry, scaler, cfg=None,
                 metrics=None, tracer=None,
                 clock: Callable[[], float] = time.monotonic,
                 drain_fn: Optional[Callable[[Replica], None]] = None,
                 slo=None, scheduler=None):
        self.registry = registry
        self.scaler = scaler
        # heterogeneity-aware placement (ISSUE 19): when a FleetScheduler
        # is wired, scale-ups REQUEST capacity through it (place() picks
        # the goodput-per-dollar pool; the scale-event reason cites the
        # choice) instead of creating pods directly, and every pod exit
        # releases its reservation. None keeps the legacy single-pool
        # create path.
        self.scheduler = scheduler
        # SLO burn-rate corroboration (ISSUE 17): when a tracker is
        # wired, latency scale-ups trigger on multi-window budget burn
        # (slo.burning) instead of the latched-p95-plus-busy heuristic —
        # a single slow beat can't scale the fleet, and a sustained
        # breach can't hide behind one fast one. None keeps the legacy
        # point-sample path.
        self.slo = slo
        self.cfg = cfg or AutoscalerConfig()
        if self.cfg.min_replicas < 0 or \
                self.cfg.max_replicas < max(1, self.cfg.min_replicas):
            raise ValueError("need 0 <= min_replicas <= max_replicas "
                             f"(got {self.cfg.min_replicas}, "
                             f"{self.cfg.max_replicas})")
        self.metrics = metrics
        self.tracer = tracer
        self.clock = clock
        self._drain_fn = drain_fn or self._http_drain
        self._over_since: Optional[float] = None
        self._under_since: Optional[float] = None
        self._last_up = -math.inf
        self._last_down = -math.inf
        self._drains: dict[str, _Drain] = {}
        # per-replica handoffs_total baselines for the prefill pool's
        # scale-down check (see _handoff_activity)
        self._last_handoffs: dict[str, int] = {}
        # pods created but whose replica hasn't registered yet: they count
        # toward fleet size, or every tick during a boot would scale again
        self._pending: dict[str, float] = {}
        # fleet-labeled pods observed with NO backing replica: first-seen
        # times for the orphan reaper (a restarted autoscaler must not
        # leak the pod of a drain its predecessor started)
        self._orphan_seen: dict[str, float] = {}
        # restart adoption (ISSUE 19) runs once, on the first tick
        self._adopted_restart = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # a role-scoped loop labels its gauge so two pool loops don't
        # clobber one series (the legacy whole-fleet loop stays unlabeled)
        self._gauge_labels = {"role": self.cfg.role} if self.cfg.role \
            else None
        if metrics is not None:
            self._describe(metrics)
            metrics.set_gauge("tpu_fleet_desired_replicas",
                              self.cfg.min_replicas,
                              labels=self._gauge_labels)

    @staticmethod
    def _describe(m):
        m.describe("tpu_fleet_desired_replicas",
                   "replica count the autoscaler is steering toward "
                   "(role-scoped pool loops label with role=)")
        m.describe("tpu_fleet_scale_ups", "scale-up actions (pods created)")
        m.describe("tpu_fleet_scale_downs",
                   "scale-down actions completed (drained pods deleted)")
        m.describe("tpu_fleet_drain_timeouts",
                   "drains force-completed after drain_timeout_s")
        m.describe("tpu_fleet_orphans_reaped",
                   "fleet-labeled pods deleted with no backing replica "
                   "(e.g. a drain orphaned by an autoscaler restart)")

    def _http_drain(self, replica: Replica):
        replica.transport.request("POST", "/drain", body={})

    # -- signal evaluation -----------------------------------------------------

    def _fleet_size(self) -> tuple[list[Replica], int]:
        """(ready replicas, effective fleet size). Size counts draining
        pods OUT (their capacity is leaving) and still-booting pods IN.
        A role-scoped loop sees only its own pool's replicas."""
        live = (self.registry.live_role(self.cfg.role) if self.cfg.role
                else self.registry.live())
        ready = [r for r in live if r.state != DRAINING]
        return ready, len(ready) + len(self._pending)

    def _overloaded(self, ready: list[Replica]) -> Optional[str]:
        if not ready:
            return None
        # the DECODE pool scales on its own signals: sustained ITL p95
        # over the SLO (the interference disaggregation removes) and free
        # KV pages running out pool-wide (admissions start failing before
        # slots do). The prefill/unified signals below — queue depth and
        # TTFT burn — stay the compute-side pair.
        if self.cfg.role == DECODE:
            if self.slo is not None:
                if self.cfg.itl_slo_s > 0 and self.slo.burning("itl"):
                    short, long_ = self.slo.burn_rates("itl")
                    return (f"itl SLO burn {short:.2f}x/{long_:.2f}x "
                            f"(short/long) over "
                            f"{self.slo.burn_threshold:.1f}x threshold")
            else:
                busy = any(r.stats.queue_depth > 0
                           or r.stats.active_slots > 0 for r in ready)
                worst_itl = max(r.stats.itl_p95_s for r in ready)
                if self.cfg.itl_slo_s > 0 \
                        and worst_itl > self.cfg.itl_slo_s and busy:
                    return f"itl_p95 {worst_itl:.4f}s over SLO " \
                           f"{self.cfg.itl_slo_s}s"
            total = sum(r.stats.kv_pages_total for r in ready)
            free = sum(r.stats.kv_pages_free for r in ready)
            if self.cfg.min_free_kv_page_frac > 0 and total > 0 \
                    and free / total < self.cfg.min_free_kv_page_frac:
                return (f"free KV pages {free}/{total} under "
                        f"{self.cfg.min_free_kv_page_frac:.0%} floor")
            return None
        queue = sum(r.stats.queue_depth for r in ready)
        if queue / len(ready) > self.cfg.target_queue_per_replica:
            return f"queue_depth {queue} over " \
                   f"{self.cfg.target_queue_per_replica}/replica"
        if self.slo is not None:
            # burn-rate corroboration (ISSUE 17): the tracker already
            # busy-gates each heartbeat observation and demands BOTH
            # windows over threshold, replacing the latched-p95+busy
            # hand-patch below
            if self.cfg.ttft_slo_s > 0 and self.slo.burning("ttft"):
                short, long_ = self.slo.burn_rates("ttft")
                return (f"ttft SLO burn {short:.2f}x/{long_:.2f}x "
                        f"(short/long) over "
                        f"{self.slo.burn_threshold:.1f}x threshold")
            return None
        worst = max(r.stats.ttft_p95_s for r in ready)
        # TTFT SLO burn needs CORROBORATING live load: the reporter's p95
        # comes from the histogram's recent tail, which has no time window
        # — after traffic stops it latches the last burst's value forever,
        # and acting on it would scale an idle fleet to max and hold it
        # there (the overload branch preempts underload)
        busy = any(r.stats.queue_depth > 0 or r.stats.active_slots > 0
                   for r in ready)
        if self.cfg.ttft_slo_s > 0 and worst > self.cfg.ttft_slo_s and busy:
            return f"ttft_p95 {worst:.3f}s over SLO {self.cfg.ttft_slo_s}s"
        return None

    def _underloaded(self, ready: list[Replica]) -> bool:
        if not ready:
            return False
        if any(r.stats.queue_depth > 0 for r in ready):
            return False
        # prefill replicas do their whole job on HTTP handler threads
        # (export_handoff never touches the scheduler queue or a slot),
        # so slot utilization below is structurally ZERO for them and
        # the sampled inflight count aliases steady short hops to idle
        # (~100ms hops vs ~2s heartbeats). The cumulative counter can't
        # alias: any hop completed since the last tick is load.
        if self.cfg.role == PREFILL and self._handoff_activity(ready):
            return False
        slots = sum(r.stats.max_slots for r in ready)
        active = sum(r.stats.active_slots for r in ready)
        if slots <= 0:
            return active == 0
        return active / slots < self.cfg.scale_down_utilization

    def _handoff_activity(self, ready: list[Replica]) -> bool:
        """Did any ready replica complete a /kv_prefill hop since the
        last check? Advances the per-replica baselines either way; a
        replica's FIRST sighting sets its baseline without counting as
        activity (registration is not load)."""
        active = False
        seen = set()
        for r in ready:
            seen.add(r.replica_id)
            total = r.stats.handoffs_total
            last = self._last_handoffs.get(r.replica_id)
            if last is not None and total > last:
                active = True
            self._last_handoffs[r.replica_id] = total
        for rid in list(self._last_handoffs):
            if rid not in seen:
                del self._last_handoffs[rid]
        return active

    # -- actions ---------------------------------------------------------------

    def _record_scale(self, direction: str, size_from: int, size_to: int,
                      reason: str, target: str = ""):
        log.info("fleet%s: scale %s %d -> %d (%s)",
                 f"[{self.cfg.role}]" if self.cfg.role else "", direction,
                 size_from, size_to, reason)
        if self.metrics is not None:
            self.metrics.set_gauge("tpu_fleet_desired_replicas", size_to,
                                   labels=self._gauge_labels)
        if self.tracer is not None:
            now = self.tracer.clock()
            self.tracer.record("fleet.scale", now, now,
                               attrs={"direction": direction,
                                      "from": size_from, "to": size_to,
                                      "reason": reason, "target": target,
                                      "role": self.cfg.role or "unified"})

    def _scale_up(self, size: int, reason: str):
        if self.scheduler is not None:
            # place-then-create: the reservation is keyed by the pod name
            # (idempotent), so a crash between place and create costs a
            # reservation the next attempt reuses — never an unaccounted
            # pod. kind = the pool role (unified for the legacy loop).
            name = self.scaler.next_name()
            placement = self.scheduler.place(
                self.cfg.role or "unified",
                getattr(self.scaler, "chips", 8) or 8, name)
            if placement is None:
                # capacity exhaustion is not an error: stay overloaded and
                # retry next tick (a drain/release may free chips)
                log.warning("fleet%s: scale up blocked — no pool has "
                            "capacity (%s)",
                            f"[{self.cfg.role}]" if self.cfg.role else "",
                            reason)
                return
            pod = self.scaler.create(name=name, placement=placement)
            reason = f"{reason}; {placement.reason}"
        else:
            pod = self.scaler.create()
        self._pending[pod] = self.clock()
        self._last_up = self.clock()
        self._over_since = None
        if self.metrics is not None:
            self.metrics.incr("tpu_fleet_scale_ups")
        self._record_scale("up", size, size + 1, reason, target=pod)

    def _start_drain(self, victim: Replica, size: int):
        try:
            self._drain_fn(victim)
        except (TransportError, CircuitOpenError) as e:
            # can't even reach it — the eviction sweep will reap it; do
            # not delete a pod whose engine may still hold live requests
            log.warning("fleet: drain of %s failed: %s", victim.replica_id, e)
            return
        self.registry.mark_draining(victim.replica_id)
        self._drains[victim.replica_id] = _Drain(
            victim.replica_id, victim.pod_name, self.clock())
        self._under_since = None
        self._record_scale("down", size, size - 1,
                           "sustained idle; draining first",
                           target=victim.replica_id)

    def _progress_drains(self):
        now = self.clock()
        for rid, drain in list(self._drains.items()):
            rep = self.registry.get(rid)
            done = rep is None or (rep.stats.draining
                                   and rep.stats.active_slots == 0
                                   and rep.stats.queue_depth == 0)
            timed_out = now - drain.started_at > self.cfg.drain_timeout_s
            if not done and not timed_out:
                continue
            if timed_out and not done and self.metrics is not None:
                self.metrics.incr("tpu_fleet_drain_timeouts")
            if rep is not None:
                self.registry.deregister(rid)
            if drain.pod_name:
                try:
                    self.scaler.delete(drain.pod_name)
                except Exception as e:  # noqa: BLE001 — retried next tick
                    log.warning("fleet: delete of %s failed (will retry): %s",
                                drain.pod_name, e)
                    continue
            if drain.pod_name and self.scheduler is not None:
                self.scheduler.release(drain.pod_name,
                                       reason="drained and deleted")
            del self._drains[rid]
            self._last_down = now
            if self.metrics is not None:
                self.metrics.incr("tpu_fleet_scale_downs")

    def _expire_pending(self):
        now = self.clock()
        registered_pods = self.registry.registered_pod_names()
        for pod, created in list(self._pending.items()):
            if pod in registered_pods:
                del self._pending[pod]
            elif now - created > self.cfg.boot_timeout_s:
                log.warning("fleet: pod %s never registered a replica in "
                            "%.0fs; dropping from fleet accounting", pod,
                            self.cfg.boot_timeout_s)
                del self._pending[pod]
                if self.scheduler is not None:
                    # its chips must not stay reserved for a pod that never
                    # came up (the orphan reaper deletes the pod itself)
                    self.scheduler.release(pod, reason="boot timeout")

    # -- the loop --------------------------------------------------------------

    def _adopt_draining(self):
        """Pick up drains this process didn't start (an operator's direct
        POST /drain, or a drain orphaned by an autoscaler restart — the
        engine's drain is irreversible, so SOMEONE must finish the
        delete): track them so _progress_drains completes them. A
        role-scoped loop adopts only ITS pool's drains — two pool loops
        double-adopting one drain would double-delete the pod."""
        live = (self.registry.live_role(self.cfg.role) if self.cfg.role
                else self.registry.live())
        for rep in live:
            if rep.state == DRAINING and rep.replica_id not in self._drains:
                log.info("fleet: adopting in-progress drain of %s",
                         rep.replica_id)
                self._drains[rep.replica_id] = _Drain(
                    rep.replica_id, rep.pod_name, self.clock())

    def _reap_orphans(self):
        """Delete fleet-labeled pods no registered replica backs (after a
        boot_timeout_s grace): a drain whose replica deregistered just as
        the autoscaler restarted leaves a pod nothing else will ever
        delete — a leaked slice serving 503s forever."""
        lister = getattr(self.scaler, "list_fleet_pods", None)
        if lister is None:
            return
        try:
            live = set(lister())
        except Exception as e:  # noqa: BLE001 — listing can flake; next tick
            log.warning("fleet: pod listing failed: %s", e)
            return
        now = self.clock()
        backed = self.registry.registered_pod_names()
        backed |= {d.pod_name for d in self._drains.values() if d.pod_name}
        backed |= set(self._pending)
        for pod in live:
            if pod in backed:
                self._orphan_seen.pop(pod, None)
                continue
            first = self._orphan_seen.setdefault(pod, now)
            if now - first <= self.cfg.boot_timeout_s:
                continue
            log.warning("fleet: reaping orphaned pod %s (no replica for "
                        "%.0fs)", pod, now - first)
            try:
                self.scaler.delete(pod)
            except Exception as e:  # noqa: BLE001 — retried next tick
                log.warning("fleet: orphan delete of %s failed: %s", pod, e)
                continue
            self._orphan_seen.pop(pod, None)
            if self.scheduler is not None:
                self.scheduler.release(pod, reason="orphan reaped")
            if self.metrics is not None:
                self.metrics.incr("tpu_fleet_orphans_reaped")
        for pod in list(self._orphan_seen):
            if pod not in live:
                del self._orphan_seen[pod]

    def _adopt_restart(self):
        """First tick after a (re)start: rebuild state from live pods.
        The scheduler re-learns every fleet pod's reservation from its
        tpu.dev/pool annotations (idempotent — already-known tags are
        skipped), and a pod created by a predecessor that hasn't
        registered a replica yet goes into _pending: it counts toward
        fleet size again (no double-place for the same demand) and gets
        the boot-timeout grace instead of being orphan-reaped."""
        self._adopted_restart = True
        if self.scheduler is None:
            return
        lister = getattr(self.scaler, "list_fleet_pod_objects", None)
        if lister is None:
            return
        try:
            pods = lister()
        except Exception as e:  # noqa: BLE001 — next restart retries
            log.warning("fleet: restart adoption listing failed: %s", e)
            return
        self.scheduler.adopt(pods)
        now = self.clock()
        backed = self.registry.registered_pod_names()
        for pod in pods:
            name = pod.get("metadata", {}).get("name", "")
            if (name and name not in backed and name not in self._pending
                    and not any(d.pod_name == name
                                for d in self._drains.values())):
                log.info("fleet: adopting pending pod %s after restart",
                         name)
                self._pending[name] = now

    def tick(self):
        now = self.clock()
        if not self._adopted_restart:
            self._adopt_restart()
        self._expire_pending()
        self._adopt_draining()
        self._progress_drains()
        self._reap_orphans()
        ready, size = self._fleet_size()
        if size < self.cfg.min_replicas:
            # the FLOOR needs no overload signal (an empty fleet reports
            # no load at all — cold start, or every replica died): fill
            # toward min_replicas, one pod per cooldown so a failing
            # create doesn't spawn a pod per tick
            if now - self._last_up >= self.cfg.scale_up_cooldown_s:
                self._scale_up(size, f"fleet size {size} below "
                                     f"min_replicas {self.cfg.min_replicas}")
            return
        overload = self._overloaded(ready)
        if overload is not None:
            self._under_since = None
            if self._over_since is None:
                self._over_since = now
            if (size < self.cfg.max_replicas
                    and now - self._over_since >= self.cfg.scale_up_stable_s
                    and now - self._last_up >= self.cfg.scale_up_cooldown_s):
                self._scale_up(size, overload)
            return
        self._over_since = None
        if self._underloaded(ready):
            if self._under_since is None:
                self._under_since = now
            if (size > self.cfg.min_replicas and not self._drains
                    and now - self._under_since
                    >= self.cfg.scale_down_stable_s
                    and now - self._last_down
                    >= self.cfg.scale_down_cooldown_s):
                # drain the least-loaded ready replica (fewest in-flight
                # requests = fastest drain); deterministic tie-break
                victim = min(ready, key=lambda r: (r.stats.load_score,
                                                   r.replica_id))
                self._start_drain(victim, size)
        else:
            self._under_since = None

    def run(self, interval_s: float = 5.0) -> "FleetAutoscaler":
        """Production loop (real sleeps); tests call tick() directly."""
        def loop():
            while not self._stop.is_set():
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — the loop must survive a bad tick
                    log.exception("autoscaler tick failed")
                self._stop.wait(interval_s)
        self._thread = threading.Thread(target=loop, name="fleet-autoscaler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5)
