"""Fleet SLO burn rates: multi-window breach fractions over heartbeats.

The autoscaler's original latency signal was a single latched TTFT p95
sample (PR 4 had to hand-patch it with a busy-gate so an idle fleet's
stale histogram tail couldn't pin scale-ups). This module replaces that
point sample with the SRE-workbook construction: each heartbeat becomes
a good/bad observation per signal, and a signal *burns* when BOTH a
short window (fast detection) and a long window (sustained evidence)
consume error budget faster than ``burn_threshold`` times the
sustainable rate.

    burn(window) = breach_fraction(window) / budget_frac

With the default budget_frac=0.05 and burn_threshold=2.0, a signal burns
when more than 10% of recent heartbeats breached the objective — on both
windows at once, so a single slow beat (short window spikes, long stays
flat) and a slowly-draining budget (long elevated, short recovered)
both stay quiet.

Signals:

- ``ttft``: heartbeat ``ttft_p95_s`` over the TTFT objective, counted
  only while the replica is BUSY (queued or active work) — an idle
  replica's histogram tail is history, not load.
- ``itl``: ``itl_p95_s`` over the ITL objective, same busy gate.
- ``error_rate``: per-replica DELTAS of the cumulative
  ``errors_total``/``requests_total`` heartbeat counters — a beat is bad
  when the interval's error ratio exceeds the objective.

Everything rides the injected clock (monotonic domain, the registry's),
so the fleet soak drives hours of burn history from one FakeClock.
Dependency-free like tracing.py/recorder.py.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Callable, Optional

from ..metrics import RestartGuard

log = logging.getLogger(__name__)

SIGNALS = ("ttft", "itl", "error_rate")
WINDOWS = ("short", "long")

# bounded burn-history ring for /debug/slo timelines (one entry per
# ingest; tools/slo_summary.py renders it)
_HISTORY_LIMIT = 512


def describe_metrics(m):
    """Register the tpu_fleet_slo_* family (called by whoever owns the
    Metrics instance — router_main's build())."""
    m.describe("tpu_fleet_slo_burn_rate",
               "error-budget burn rate per SLO signal and window "
               "(labels: signal=ttft|itl|error_rate, window=short|long); "
               "burn = breach fraction / budget fraction, >1 consumes "
               "budget faster than sustainable")
    m.describe("tpu_fleet_slo_crossings",
               "burn-rate threshold crossings (onsets, edge-triggered "
               "per signal; labels: signal=ttft|itl|error_rate)")


class SLOTracker:
    """Multi-window burn-rate evaluation over registry heartbeats.

    ``ingest(replica_id, stats)`` is called per accepted heartbeat (the
    registry does it outside its membership lock); ``burning(signal)``
    is the autoscaler's corroboration read; ``snapshot()`` backs the
    router's ``GET /debug/slo``. Thread-safe: heartbeats arrive on HTTP
    handler threads while the autoscaler reads from its tick thread.
    """

    def __init__(self, ttft_slo_s: float = 2.0, itl_slo_s: float = 0.25,
                 error_rate_slo: float = 0.01,
                 short_window_s: float = 300.0,
                 long_window_s: float = 3600.0,
                 burn_threshold: float = 2.0,
                 budget_frac: float = 0.05,
                 metrics=None, tracer=None,
                 clock: Callable[[], float] = time.monotonic):
        self.objectives = {"ttft": ttft_slo_s, "itl": itl_slo_s,
                           "error_rate": error_rate_slo}
        self.short_window_s = short_window_s
        self.long_window_s = long_window_s
        self.burn_threshold = burn_threshold
        self.budget_frac = budget_frac
        self.metrics = metrics
        self.tracer = tracer
        self.clock = clock
        self._lock = threading.Lock()
        # per-signal deques of (t, breached) observations, pruned past
        # the long window (the short window is a suffix of the long one)
        self._samples = {s: collections.deque() for s in SIGNALS}
        # per-replica cumulative-counter guards for error-rate deltas
        # (metrics.RestartGuard, extracted from the idiom born here):
        # first sighting and post-restart beats both contribute ZERO —
        # an old error total is history, not a fresh breach signal
        self._err_guard = RestartGuard(count_first=False,
                                       count_restart=False)
        self._req_guard = RestartGuard(count_first=False,
                                       count_restart=False)
        self._burning = {s: False for s in SIGNALS}
        self._crossings = {s: 0 for s in SIGNALS}
        self._history = collections.deque(maxlen=_HISTORY_LIMIT)
        if metrics is not None:
            describe_metrics(metrics)
            for sig in SIGNALS:
                self._crossing_seed(sig)

    def _crossing_seed(self, sig: str):
        # zero-seed so "crossings == 0" is a rendered fact, not a
        # missing series (the stalled-gauge lesson from PR 5)
        self.metrics.incr("tpu_fleet_slo_crossings", 0,
                          labels={"signal": sig})

    # -- ingest ----------------------------------------------------------------

    def ingest(self, replica_id: str, stats) -> None:
        """Fold one heartbeat into the windows. ``stats`` is the
        registry's ReplicaStats (or any object with its attributes)."""
        now = self.clock()
        busy = (int(getattr(stats, "queue_depth", 0)) > 0
                or int(getattr(stats, "active_slots", 0)) > 0)
        obs = {
            # busy-gated latency breaches: an idle replica observes a
            # GOOD sample (its histogram tail is stale, not evidence),
            # keeping the denominator honest while traffic pauses
            "ttft": busy and float(getattr(stats, "ttft_p95_s", 0.0))
            > self.objectives["ttft"],
            "itl": busy and float(getattr(stats, "itl_p95_s", 0.0))
            > self.objectives["itl"],
            "error_rate": self._error_breach(replica_id, stats),
        }
        spans = []
        with self._lock:
            for sig, breached in obs.items():
                dq = self._samples[sig]
                dq.append((now, bool(breached)))
                self._prune(dq, now)
            burns = {sig: (self._burn(sig, now, self.short_window_s),
                           self._burn(sig, now, self.long_window_s))
                     for sig in SIGNALS}
            for sig, (short, long_) in burns.items():
                burning = (short >= self.burn_threshold
                           and long_ >= self.burn_threshold)
                if burning and not self._burning[sig]:
                    # onset, edge-triggered: one span + one crossing
                    # count per excursion, not per beat inside it
                    self._crossings[sig] += 1
                    spans.append((sig, short, long_))
                self._burning[sig] = burning
            self._history.append(
                (round(now, 3),
                 {sig: round(b[0], 3) for sig, b in burns.items()}))
        if self.metrics is not None:
            for sig, (short, long_) in burns.items():
                self.metrics.set_gauge(
                    "tpu_fleet_slo_burn_rate", round(short, 4),
                    labels={"signal": sig, "window": "short"})
                self.metrics.set_gauge(
                    "tpu_fleet_slo_burn_rate", round(long_, 4),
                    labels={"signal": sig, "window": "long"})
            for sig, _, _ in spans:
                self.metrics.incr("tpu_fleet_slo_crossings",
                                  labels={"signal": sig})
        for sig, short, long_ in spans:
            log.warning(
                "fleet: SLO burn crossing on %s (short=%.2fx long=%.2fx, "
                "threshold %.2fx of budget_frac=%.3f)", sig, short, long_,
                self.burn_threshold, self.budget_frac)
            if self.tracer is not None:
                self.tracer.record(
                    "fleet.slo_burn", now, now,
                    attrs={"signal": sig, "short_burn": round(short, 4),
                           "long_burn": round(long_, 4),
                           "threshold": self.burn_threshold,
                           "objective": self.objectives[sig],
                           "replica_id": replica_id})

    def _error_breach(self, replica_id: str, stats) -> bool:
        # guards zero out first-sight and restart beats, so a replica
        # restart (counters going backwards) re-baselines instead of
        # subtracting its whole history — and a beat where only ONE
        # counter reset still can't breach (d_req clamps to 0)
        d_err = self._err_guard.delta(
            replica_id, int(getattr(stats, "errors_total", 0)))
        d_req = self._req_guard.delta(
            replica_id, int(getattr(stats, "requests_total", 0)))
        if d_req <= 0:
            return False
        return d_err / d_req > self.objectives["error_rate"]

    def _prune(self, dq, now: float):
        horizon = now - self.long_window_s
        while dq and dq[0][0] < horizon:
            dq.popleft()

    def _burn(self, sig: str, now: float, window_s: float) -> float:
        """breach_fraction(window) / budget_frac (0.0 with no samples)."""
        cutoff = now - window_s
        total = bad = 0
        for t, breached in self._samples[sig]:
            if t >= cutoff:
                total += 1
                bad += breached
        if total == 0:
            return 0.0
        return (bad / total) / self.budget_frac

    def forget(self, replica_id: str) -> None:
        """Drop a replica's error-counter baseline (evict/deregister):
        its next registration starts a fresh delta stream."""
        with self._lock:
            self._err_guard.forget(replica_id)
            self._req_guard.forget(replica_id)

    # -- reads -----------------------------------------------------------------

    def burning(self, signal: str) -> bool:
        """The autoscaler's corroboration read: is this signal consuming
        error budget faster than threshold on BOTH windows right now?"""
        with self._lock:
            return self._burning.get(signal, False)

    def burn_rates(self, signal: str) -> tuple[float, float]:
        """(short, long) burn for one signal, recomputed at read time so
        an ingest-quiet fleet still decays toward zero."""
        now = self.clock()
        with self._lock:
            return (self._burn(signal, now, self.short_window_s),
                    self._burn(signal, now, self.long_window_s))

    def snapshot(self) -> dict:
        """The ``GET /debug/slo`` payload (tools/slo_summary.py renders
        it): objectives, per-signal burn state, and the bounded burn
        history for timelines."""
        now = self.clock()
        with self._lock:
            signals = {}
            for sig in SIGNALS:
                short = self._burn(sig, now, self.short_window_s)
                long_ = self._burn(sig, now, self.long_window_s)
                dq = self._samples[sig]
                cutoff = now - self.short_window_s
                signals[sig] = {
                    "objective": self.objectives[sig],
                    "burning": self._burning[sig],
                    "short_burn": round(short, 4),
                    "long_burn": round(long_, 4),
                    "crossings": self._crossings[sig],
                    "samples_long": len(dq),
                    "samples_short": sum(1 for t, _ in dq if t >= cutoff),
                }
            history = [{"t": t, "burn": dict(b)} for t, b in self._history]
        return {"enabled": True,
                "schema_version": 1,
                "burn_threshold": self.burn_threshold,
                "budget_frac": self.budget_frac,
                "windows": {"short_s": self.short_window_s,
                            "long_s": self.long_window_s},
                "signals": signals,
                "history": history}
