"""Fleet request router: one front door over many serving replicas.

Speaks the same ``/v1/*`` + ``/generate`` API as serve_main, so clients
(and OpenAI SDKs) point here unchanged and the fleet scales behind them.
Per request:

- **pick** a replica: prefix-affinity first (a stable hash of the session
  id / prompt prefix pins a conversation to the replica holding its
  prefix cache — rendezvous hashing, so membership churn only remaps the
  dead replica's keys), falling back to least-loaded (queue + active -
  free slots, TTFT p95 breaking ties) when the pinned replica is
  saturated or gone;
- **forward** with the router's span id in the outbound ``traceparent``,
  so the engine's ``serving.request`` tree parents under this router's
  ``fleet.route`` span and one trace_id spans both layers;
- **fail over**: a 5xx/network failure on an idempotent non-streamed
  request marks the replica's breaker and retries on the next-best
  replica (the generation never ran to completion on the corpse, so the
  retry is safe); per-replica 429s try the next replica too;
- **admission**: when every routable replica is saturated the router
  answers 429 + Retry-After itself (serve_main's bounded-latency
  contract, fleet-wide);
- **stream passthrough**: SSE/NDJSON bytes relay chunk-by-chunk as they
  arrive (never buffering the stream); a replica dying mid-stream ends
  the client's chunked stream CLEANLY (terminator sent, counter bumped)
  instead of hanging the connection.
"""

from __future__ import annotations

import dataclasses
import hashlib
import http.client
import json
import logging
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from ..cloud.transport import CircuitOpenError, TransportError
from ..tracing import Tracer, format_traceparent, parse_traceparent
from .registry import DECODE, PREFILL, UNIFIED, Replica, ReplicaRegistry

log = logging.getLogger(__name__)

# routes forwarded to exactly one replica (the serving API surface)
_FORWARD_ROUTES = ("/generate", "/v1/completions", "/v1/chat/completions",
                   "/v1/embeddings")
# sub-second buckets: routing adds network hops, not decode steps
_ROUTE_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                  1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)


@dataclasses.dataclass
class RouterConfig:
    port: int = 8090
    # how many distinct replicas one request may try before giving up
    max_attempts: int = 3
    # chars of prompt text / count of prompt tokens hashed for
    # prefix-affinity when the request has no session id
    affinity_prefix_chars: int = 64
    affinity_prefix_tokens: int = 32
    request_timeout_s: float = 120.0
    retry_after_s: int = 1
    # disaggregated serving (ISSUE 9): budget for the prefill hop (the
    # prefill replica's compute + its page push to the decode replica);
    # a hop that outlives it falls back to a single-hop route
    handoff_timeout_s: float = 30.0
    # device-native KV transfer (ISSUE 11): when both hop replicas
    # advertise the SAME non-empty placement domain, ask the prefill
    # replica to hand pages arena-to-arena (zero host copies); it
    # downgrades to the wire codec itself on any device-path failure.
    # False = every hop rides the wire.
    device_transfer_enabled: bool = True
    # fleet-wide KV fabric (ISSUE 16): consult the global prefix
    # directory on token-prompt requests and plan a pull hop — the
    # picked replica fetches the cached page run from a holder instead
    # of re-prefilling. The pull is strictly an optimization; any
    # failure falls back to the normal prefill path.
    prefix_directory_enabled: bool = True
    # budget for one /kv_fetch pull hop (lookup is in-process and free)
    pull_timeout_s: float = 10.0
    # legacy /prefix fan-out (pre-directory): register on EVERY replica
    # up front instead of one replica + lazy pulls
    prefix_broadcast: bool = False
    # page granule for router-side prefix keys — MUST match the fleet's
    # ServingConfig.kv_page_tokens or directory keys never match
    kv_page_tokens: int = 16


def affinity_key_for(path: str, body: dict, prefix_chars: int = 64,
                     prefix_tokens: int = 32) -> str:
    """The prefix-affinity key: an explicit session/user id when the
    client sent one (conversations stay pinned across turns), else the
    prompt's own prefix (same system prompt -> same replica -> its
    registered prefix cache keeps hitting). The prefix lengths come from
    RouterConfig (the router passes its own)."""
    if not isinstance(body, dict):
        return ""
    for field in ("session_id", "user"):
        v = body.get(field)
        if isinstance(v, str) and v:
            return f"sid:{v}"
    if path == "/v1/chat/completions":
        msgs = body.get("messages")
        if isinstance(msgs, list) and msgs and isinstance(msgs[0], dict):
            head = str(msgs[0].get("content", ""))[:prefix_chars]
            return f"chat:{head}" if head else ""
        return ""
    prompt = body.get("prompt", body.get("tokens", body.get("text")))
    if isinstance(prompt, str) and prompt:
        return f"txt:{prompt[:prefix_chars]}"
    if isinstance(prompt, list) and prompt:
        head = prompt[:prefix_tokens]
        return "tok:" + ",".join(str(t) for t in head)
    return ""


class FleetRouter:
    """Routing policy + forwarding machinery (transport-level); the HTTP
    handler below is a thin shim over ``forward``/``stream_forward``."""

    def __init__(self, registry: ReplicaRegistry, cfg: RouterConfig = None,
                 metrics=None, tracer: Optional[Tracer] = None,
                 clock: Callable[[], float] = time.monotonic,
                 directory=None, slo=None, scheduler=None):
        self.registry = registry
        self.cfg = cfg or RouterConfig()
        # SLO burn-rate tracker (ISSUE 17) behind GET /debug/slo; the
        # registry feeds it heartbeats, the autoscaler reads burning()
        self.slo = slo
        # fleet scheduler (ISSUE 19) behind GET /debug/scheduler; the
        # pool autoscalers request capacity through it
        self.scheduler = scheduler
        self.metrics = metrics
        self.tracer = tracer if tracer is not None else Tracer()
        self.clock = clock
        # global prefix directory (ISSUE 16) — router_main wires the
        # SAME instance into the registry (publish/evict) and here
        # (lookup/invalidate); None = directory routing off
        self.directory = directory
        if metrics is not None:
            self._describe(metrics)
            # scrape-from-start: the dashboards' series must exist before
            # the first routed request
            metrics.incr("tpu_fleet_requests", 0, labels={"outcome": "ok"})
            metrics.incr("tpu_fleet_handoffs", 0, labels={"outcome": "ok"})

    @staticmethod
    def _describe(m):
        m.describe("tpu_fleet_requests",
                   "requests routed through the fleet front door "
                   "(labels: outcome=ok|rejected|failed|no_replicas)")
        m.describe("tpu_fleet_failovers",
                   "mid-call replica failures retried on the next replica")
        m.describe("tpu_fleet_stream_aborted",
                   "streams cleanly truncated by a replica dying mid-stream")
        m.describe("tpu_fleet_rejected_saturated",
                   "requests 429-rejected with every replica saturated")
        m.describe("tpu_fleet_route_seconds",
                   "router-side request latency (pick + forward + relay)",
                   buckets=_ROUTE_BUCKETS)
        m.describe("tpu_fleet_handoffs",
                   "disaggregated prefill->decode KV handoffs (labels: "
                   "outcome=ok|failed|skipped; skipped = the prefill "
                   "replica declined without computing — prompt under one "
                   "page or an untokenizable route — an expected quiet "
                   "fallback, not a failure)")
        m.describe("tpu_fleet_handoff_seconds",
                   "prefill-hop latency: prefill compute + page push to "
                   "the decode replica", buckets=_ROUTE_BUCKETS)

    # -- picking ---------------------------------------------------------------

    @staticmethod
    def _rendezvous(key: str, replica_id: str) -> int:
        return int.from_bytes(hashlib.sha256(
            f"{key}|{replica_id}".encode()).digest()[:8], "big")

    def pick(self, affinity_key: str = "",
             exclude: frozenset = frozenset(),
             roles: Optional[tuple] = None) -> tuple[Optional[Replica], str]:
        """(replica, reason) — reason names the policy leg that chose it
        (exported on the fleet.route span for tools/fleet_summary.py).
        ``roles`` restricts candidates to those pools (None = any)."""
        candidates = [r for r in self.registry.ready()
                      if r.replica_id not in exclude
                      and (roles is None or r.role in roles)]
        if not candidates:
            return None, "no_replicas"
        if affinity_key:
            pinned = max(candidates,
                         key=lambda r: self._rendezvous(affinity_key,
                                                        r.replica_id))
            if not pinned.stats.saturated:
                return pinned, "affinity"
        best = min(candidates,
                   key=lambda r: (r.stats.load_score, r.stats.ttft_p95_s,
                                  r.replica_id))
        return best, "least_loaded"

    def disagg_ready(self) -> bool:
        """Two-hop routing is on the table: both role pools have a ready
        member. Role presence IS the mode switch — an all-unified fleet
        routes exactly as before."""
        return bool(self.registry.ready(PREFILL)) \
            and bool(self.registry.ready(DECODE))

    def _single_hop_roles(self, tried: frozenset = frozenset()
                          ) -> Optional[tuple]:
        """Candidate pools for a single-hop route (the non-disaggregated
        path AND the fallback when a pool is empty or a handoff failed):
        unified replicas first — they exist to absorb exactly this — and
        only when none are USABLE, any pool (every engine can prefill for
        itself, just without the batch-shape isolation). ``tried`` is the
        attempt loop's exclusion set: once every unified replica has
        failed this request, retries must widen to the role pools rather
        than dead-end on an exhausted unified pool."""
        if any(r.replica_id not in tried
               for r in self.registry.ready(UNIFIED)):
            return (UNIFIED,)
        return None

    def all_saturated(self) -> bool:
        ready = self.registry.ready()
        return bool(ready) and all(r.stats.saturated for r in ready)

    def _affinity_key(self, path: str, body: dict) -> str:
        return affinity_key_for(path, body,
                                prefix_chars=self.cfg.affinity_prefix_chars,
                                prefix_tokens=self.cfg.affinity_prefix_tokens)

    # -- tracing ---------------------------------------------------------------

    def trace_ctx(self, inbound_header: Optional[str]) -> dict:
        """Per-request trace context: the inbound traceparent's trace_id
        (caller owns the trace) or a fresh one; a router span id minted
        NOW so the outbound traceparent makes the engine's request tree a
        CHILD of the router's fleet.route span."""
        inbound = parse_traceparent(inbound_header)
        trace_id = inbound[0] if inbound else Tracer.new_trace_id()
        span_id = Tracer.new_span_id()
        return {"trace_id": trace_id, "span_id": span_id,
                "parent_id": inbound[1] if inbound else "",
                "header": format_traceparent(trace_id, span_id)}

    def _record_route(self, trace: dict, path: str, started_mono: float,
                      replica_id: str, status: int, reason: str,
                      attempts: int, streamed: bool):
        dur = self.clock() - started_mono
        if self.metrics is not None:
            self.metrics.observe("tpu_fleet_route_seconds", dur)
        end = self.tracer.clock()
        try:
            self.tracer.record("fleet.route", end - dur, end,
                               trace_id=trace["trace_id"],
                               span_id=trace["span_id"],
                               parent_id=trace["parent_id"],
                               attrs={"path": path, "replica_id": replica_id,
                                      "status": status, "reason": reason,
                                      "attempts": attempts,
                                      "streamed": streamed})
        except Exception:  # noqa: BLE001 — tracing must never fail a request
            log.exception("fleet.route span recording failed")

    def _outcome(self, outcome: str):
        if self.metrics is not None:
            self.metrics.incr("tpu_fleet_requests",
                              labels={"outcome": outcome})

    # -- directory pull hop (ISSUE 16) -----------------------------------------

    @staticmethod
    def _token_prompt(path: str, body: dict) -> Optional[list]:
        """The request's prompt as a TOKEN list, or None. The router has
        no tokenizer, so directory keys (token-space hashes) are only
        computable for routes that carry tokens directly; text prompts
        keep riding rendezvous affinity + local prefill (documented
        limitation in the README)."""
        if not isinstance(body, dict):
            return None
        if path == "/generate":
            prompt = body.get("tokens")
        elif path == "/v1/completions":
            prompt = body.get("prompt")
        else:
            return None
        if (isinstance(prompt, list) and prompt
                and all(isinstance(t, int) for t in prompt)):
            return prompt
        return None

    def maybe_pull(self, path: str, payload: dict, replica: Replica,
                   trace: dict) -> None:
        """Directory-planned pull hop: when the replica about to serve
        this request is NOT a holder of its longest cached prefix, ask it
        (POST /kv_fetch) to fetch the page run from a holder over the
        fastest reachable rung before the forward lands — adoption
        instead of re-prefill. Strictly best-effort and never raises: a
        miss, a gone (holder evicted since publish — invalidate the
        claim, no retry), or any transport failure just leaves the
        request on its normal prefill path. One fleet.directory_lookup
        span per consulted request records the outcome."""
        if (not self.cfg.prefix_directory_enabled
                or self.directory is None or replica is None):
            return
        tokens = self._token_prompt(path, payload)
        if not tokens or len(tokens) < self.cfg.kv_page_tokens:
            return
        adapter = str(payload.get("adapter") or "")
        started = self.clock()
        span_id = Tracer.new_span_id()
        outcome, hit_key, owner_id, pull_path, pages = "miss", "", "", "", 0
        try:
            from .prefix_directory import prefix_key_chain
            chain = prefix_key_chain(tokens, self.cfg.kv_page_tokens,
                                     adapter)
            # longest-first: the deepest cached prefix wins
            found = self.directory.lookup(list(reversed(chain)))
            if found is None:
                return
            hit_key, entry = found
            holders = set(entry.get("holders") or [])
            if replica.replica_id in holders:
                outcome = "local"  # the pick already holds the pages
                return
            ready = {r.replica_id: r for r in self.registry.ready()}
            owners = [ready[h] for h in sorted(holders) if h in ready]
            if not owners:
                outcome = "no_owner"
                return
            # prefer a same-domain holder: the pull can then ride the
            # device/shm rungs instead of the wire
            domain = replica.placement_domain
            owner = next((o for o in owners
                          if domain and o.placement_domain == domain),
                         owners[0])
            owner_id = owner.replica_id
            out = replica.transport.request(
                "POST", "/kv_fetch",
                body={"tokens": tokens, "adapter": adapter,
                      "owner_url": owner.base_url,
                      "owner_domain": owner.placement_domain,
                      "model": str(entry.get("model") or "")},
                timeout_s=self.cfg.pull_timeout_s,
                extra_headers={"traceparent": format_traceparent(
                    trace["trace_id"], span_id)})
            if isinstance(out, dict) and out.get("ok"):
                outcome = "pulled"
                pull_path = str(out.get("path") or "")
                pages = int(out.get("pages") or 0)
            elif isinstance(out, dict) and out.get("gone"):
                # the holder's trie evicted the run since its publish:
                # drop THAT claim and fall back to prefill — one miss,
                # one invalidation, no retry storm
                outcome = "gone"
                self.directory.invalidate(hit_key, owner_id,
                                          reason="gone")
            else:
                outcome = "failed"
        except (CircuitOpenError, TransportError) as e:
            outcome = "failed"
            log.debug("fleet: pull hop to %s failed: %s",
                      replica.replica_id, e)
        except Exception:  # noqa: BLE001 — a pull must never fail a request
            outcome = "failed"
            log.exception("fleet: directory pull planning failed")
        finally:
            dur = self.clock() - started
            end = self.tracer.clock()
            try:
                self.tracer.record(
                    "fleet.directory_lookup", end - dur, end,
                    trace_id=trace["trace_id"], span_id=span_id,
                    parent_id=trace["span_id"],
                    attrs={"outcome": outcome, "key": hit_key,
                           "owner": owner_id,
                           "replica_id": replica.replica_id,
                           "path": pull_path, "pages": pages})
            except Exception:  # noqa: BLE001 — tracing must never fail a request
                log.exception("fleet.directory_lookup span recording "
                              "failed")

    # -- disaggregated two-hop (ISSUE 9) ---------------------------------------

    def plan_two_hop(self, path: str, payload: dict, key: str,
                     trace: dict) -> Optional[Replica]:
        """The prefill hop: pick one replica per pool (prefix-affinity on
        BOTH — the prefill replica's own trie hit shrinks its compute,
        the decode replica accumulates a conversation's adopted pages),
        POST /kv_prefill on the prefill replica (it computes the KV and
        pushes the page run straight to the decode replica's /kv_adopt),
        and return the decode replica the request should now be forwarded
        to. Returns None when either pool is empty or the hop failed —
        the caller falls back to a single-hop route (the decision table
        in the README). A ``fleet.handoff`` span child of this request's
        fleet.route records the hop; the engines' serving.kv_prefill /
        serving.kv_adopt spans parent under it via the traceparent it
        forwards, joining both engines under one trace_id."""
        decode_rep, _ = self.pick(key, roles=(DECODE,))
        prefill_rep, _ = self.pick(key, roles=(PREFILL,))
        if decode_rep is None or prefill_rep is None:
            return None
        started = self.clock()
        span_id = Tracer.new_span_id()
        ok, skipped, pages, nbytes, err = False, False, 0, 0, ""
        streamed, chunks, overlap = False, 0, None
        # device-path annotation (ISSUE 11): same non-empty placement
        # domain on both replicas = the prefill side may hand pages
        # arena-to-arena. The router only ANNOTATES; the prefill replica
        # decides per hop and reports the path it actually took (it
        # downgrades device -> wire itself on any failure).
        domain = prefill_rep.placement_domain
        device_ok = bool(self.cfg.device_transfer_enabled and domain
                         and domain == decode_rep.placement_domain)
        hop_path = "wire"
        try:
            out = prefill_rep.transport.request(
                "POST", "/kv_prefill",
                body={"path": path, "request": payload,
                      "handoff_to": decode_rep.base_url,
                      "device": device_ok,
                      # the hop's shared placement domain: on a bus miss
                      # the sender cannot see the peer's domain locally,
                      # and the cross-process shm rung needs to know the
                      # target is the same host (ISSUE 16)
                      "device_domain": domain if device_ok else ""},
                timeout_s=self.cfg.handoff_timeout_s,
                extra_headers={"traceparent": format_traceparent(
                    trace["trace_id"], span_id)})
            if isinstance(out, dict) and out.get("ok"):
                ok = True
                pages = int(out.get("pages") or 0)
                nbytes = int(out.get("bytes") or 0)
                hop_path = str(out.get("path") or "wire")
                # streamed hop (ISSUE 10): chunk count + realized
                # compute/transfer overlap ride the fleet.handoff span
                # (fleet_summary's overlap column)
                streamed = bool(out.get("streamed"))
                chunks = int(out.get("chunks") or 0)
                overlap = out.get("overlap_ratio")
            elif isinstance(out, dict) and out.get("skip"):
                # the prefill replica DECLINED without computing (prompt
                # under one page, no tokenizer for this route): an
                # expected condition, not a failure — fall back quietly
                # and keep the failure series meaningful for alerts
                skipped = True
                err = str(out.get("error") or "skipped")
            else:
                err = f"unexpected /kv_prefill reply: {out!r}"
        except (CircuitOpenError, TransportError) as e:
            err = str(e)
        dur = self.clock() - started
        outcome = "ok" if ok else ("skipped" if skipped else "failed")
        if self.metrics is not None:
            self.metrics.incr("tpu_fleet_handoffs",
                              labels={"outcome": outcome})
            self.metrics.observe("tpu_fleet_handoff_seconds", dur)
        end = self.tracer.clock()
        try:
            self.tracer.record(
                "fleet.handoff", end - dur, end,
                trace_id=trace["trace_id"], span_id=span_id,
                parent_id=trace["span_id"],
                attrs={"prefill_replica": prefill_rep.replica_id,
                       "decode_replica": decode_rep.replica_id,
                       "ok": ok, "outcome": outcome, "pages": pages,
                       "bytes": nbytes, "streamed": streamed,
                       "chunks": chunks, "overlap_ratio": overlap,
                       # the transfer path the hop ACTUALLY took
                       # (device|wire) + the co-location the router saw:
                       # fleet_summary rolls handoffs up per path/domain
                       "path": hop_path,
                       "domain": domain if device_ok else "",
                       "error": err or None})
        except Exception:  # noqa: BLE001 — tracing must never fail a request
            log.exception("fleet.handoff span recording failed")
        if skipped:
            log.debug("fleet: handoff %s -> %s skipped (%s)",
                      prefill_rep.replica_id, decode_rep.replica_id, err)
            return None
        if not ok:
            log.warning("fleet: handoff %s -> %s failed (%s); falling "
                        "back to single-hop", prefill_rep.replica_id,
                        decode_rep.replica_id, err)
            return None
        return decode_rep

    # -- non-streamed forwarding -----------------------------------------------

    def forward(self, path: str, payload: dict, trace: dict,
                tenant: str = "") -> tuple[int, dict, dict]:
        """Route one idempotent non-streamed request. Returns (status,
        body, extra response headers). Generation requests are idempotent
        from the fleet's view — a replica that died mid-call never
        completed the generation, so re-running it elsewhere double-spends
        some decode steps but never double-delivers a result."""
        started = self.clock()
        headers = {"traceparent": trace["header"]}
        # tenant attribution (ISSUE 20): the front door's X-Tenant rides
        # to the replica so the cost meter books the request to its payer
        fwd_headers = {"traceparent": trace["header"]}
        if tenant:
            fwd_headers["X-Tenant"] = tenant
        if self.all_saturated():
            self._outcome("rejected")
            if self.metrics is not None:
                self.metrics.incr("tpu_fleet_rejected_saturated")
            self._record_route(trace, path, started, "", 429,
                               "all_saturated", 0, False)
            return (429, {"error": {"message": "every replica is saturated; "
                                               "retry later",
                                    "type": "overloaded_error"}},
                    {**headers, "Retry-After": str(self.cfg.retry_after_s)})
        key = self._affinity_key(path, payload)
        # disaggregated two-hop: prefill hop first, then forward to the
        # decode replica it fed. Embeddings stay single-hop (no KV to
        # move); a failed/unavailable hop falls back to the unified pool
        preferred: Optional[Replica] = None
        if path != "/v1/embeddings" and self.disagg_ready():
            preferred = self.plan_two_hop(path, payload, key, trace)
        tried: set[str] = set()
        last: Optional[TransportError] = None
        reason = "no_replicas"
        attempts = 0
        for _ in range(max(1, self.cfg.max_attempts)):
            if preferred is not None:
                replica, reason = preferred, "two_hop"
                preferred = None
            else:
                excl = frozenset(tried)
                replica, reason = self.pick(
                    key, exclude=excl, roles=self._single_hop_roles(excl))
            if replica is None:
                break
            attempts += 1
            tried.add(replica.replica_id)
            if attempts == 1 and reason != "two_hop":
                # directory pull (ISSUE 16): give a cold pick the chance
                # to adopt this prompt's cached pages from a holder
                # before the forward lands (two-hop decode replicas just
                # adopted via the handoff — nothing to pull)
                self.maybe_pull(path, payload, replica, trace)
            try:
                out = replica.transport.request(
                    "POST", path, body=payload,
                    timeout_s=self.cfg.request_timeout_s,
                    extra_headers=fwd_headers)
                self._outcome("ok")
                self._record_route(trace, path, started, replica.replica_id,
                                   200, reason, attempts, False)
                return 200, (out if isinstance(out, dict) else {}), headers
            except CircuitOpenError:
                # fail-fast skip: no I/O happened, don't count a failover
                continue
            except TransportError as e:
                last = e
                if e.status == 429:
                    # THIS replica is full; another may admit (stats lag)
                    continue
                if 400 <= e.status < 500:
                    # deterministic client error: relay verbatim, no failover
                    self._outcome("rejected")
                    self._record_route(trace, path, started,
                                       replica.replica_id, e.status, reason,
                                       attempts, False)
                    return e.status, self._error_body(e), headers
                # network/5xx: the replica is (half-)dead — its breaker
                # already recorded the failure; try the next-best one
                if self.metrics is not None:
                    self.metrics.incr("tpu_fleet_failovers")
                log.warning("fleet: %s on %s failed (%s); failing over",
                            path, replica.replica_id, e)
                continue
        if last is not None and last.status == 429:
            self._outcome("rejected")
            if self.metrics is not None:
                self.metrics.incr("tpu_fleet_rejected_saturated")
            self._record_route(trace, path, started, "", 429, "saturated",
                               attempts, False)
            return (429, self._error_body(last),
                    {**headers, "Retry-After": str(self.cfg.retry_after_s)})
        if attempts == 0:
            self._outcome("no_replicas")
            self._record_route(trace, path, started, "", 503, reason, 0,
                               False)
            return (503, {"error": {"message": "no ready replicas",
                                    "type": "overloaded_error"}},
                    {**headers, "Retry-After": str(self.cfg.retry_after_s)})
        self._outcome("failed")
        self._record_route(trace, path, started, "", 502, "exhausted",
                           attempts, False)
        return (502, {"error": {"message": f"all {attempts} replica "
                                           f"attempt(s) failed: {last}",
                                "type": "server_error"}}, headers)

    @staticmethod
    def _error_body(e: TransportError) -> dict:
        try:
            body = json.loads(e.body) if e.body else None
        except json.JSONDecodeError:
            body = None
        if isinstance(body, dict):
            return body
        return {"error": {"message": str(e), "type": "server_error"}}

    # -- streamed forwarding ---------------------------------------------------

    def open_stream(self, path: str, raw_body: bytes, trace: dict,
                    prefer: Optional[Replica] = None,
                    key: Optional[str] = None, tenant: str = ""
                    ) -> tuple[Optional[Replica], object, object,
                               str, int]:
        """Pick a replica and open the upstream response WITHOUT reading
        its body. Failover happens only HERE (before any byte reached the
        client); once the stream is open the relay is committed to this
        replica. ``prefer`` pins the first attempt (the two-hop decode
        replica whose arena just adopted this prompt's KV); later
        attempts fall back through the single-hop pools. ``key`` is the
        precomputed affinity key when the caller already parsed the body
        (the two-hop planner did). Returns
        (replica, conn, resp, reason, attempts) — replica None means no
        stream could be opened (resp carries (status, body, headers) for
        a plain error response instead)."""
        if key is None:
            key = self._affinity_key(path, self._safe_json(raw_body))
        tried: set[str] = set()
        attempts = 0
        last_err: tuple[int, dict, dict] = (
            503, {"error": {"message": "no ready replicas",
                            "type": "overloaded_error"}},
            {"Retry-After": str(self.cfg.retry_after_s)})
        for _ in range(max(1, self.cfg.max_attempts)):
            if prefer is not None:
                replica, reason = prefer, "two_hop"
                prefer = None
            else:
                excl = frozenset(tried)
                replica, reason = self.pick(
                    key, exclude=excl, roles=self._single_hop_roles(excl))
            if replica is None:
                break
            attempts += 1
            tried.add(replica.replica_id)
            if attempts == 1 and reason != "two_hop":
                # same pre-forward pull chance as forward() — streamed
                # requests re-prefill identically without it
                self.maybe_pull(path, self._safe_json(raw_body), replica,
                                trace)
            breaker = replica.transport.breaker
            if breaker is not None and not breaker.allow():
                continue
            parsed = urllib.parse.urlsplit(replica.base_url)
            conn = http.client.HTTPConnection(
                parsed.hostname, parsed.port or 80,
                timeout=self.cfg.request_timeout_s)
            stream_headers = {"Content-Type": "application/json",
                              "traceparent": trace["header"]}
            if tenant:
                stream_headers["X-Tenant"] = tenant
            try:
                conn.request("POST", path, body=raw_body,
                             headers=stream_headers)
                resp = conn.getresponse()
            except OSError as e:
                if breaker is not None:
                    breaker.record_failure()
                if self.metrics is not None:
                    self.metrics.incr("tpu_fleet_failovers")
                log.warning("fleet: stream open to %s failed (%s)",
                            replica.replica_id, e)
                conn.close()
                continue
            if resp.status >= 500:
                # the replica's engine is sick; no byte has reached the
                # client yet, so this is still failover territory — and
                # the breaker must LEARN (an all-streaming workload would
                # otherwise pin a corpse forever: success below would keep
                # its breaker closed and sweep() would never suspect it)
                if breaker is not None:
                    breaker.record_failure()
                if self.metrics is not None:
                    self.metrics.incr("tpu_fleet_failovers")
                log.warning("fleet: stream open to %s answered %d; "
                            "failing over", replica.replica_id, resp.status)
                last_err = (502, self._read_error_body(resp) or
                            {"error": {"message": "replica error",
                                       "type": "server_error"}}, {})
                conn.close()
                continue
            if breaker is not None:
                breaker.record_success()  # a non-5xx answer: alive
            if resp.status != 200:
                body = self._read_error_body(resp)
                conn.close()
                if resp.status == 429:
                    last_err = (429, body or {"error": {
                        "message": "replica saturated",
                        "type": "overloaded_error"}},
                        {"Retry-After": str(self.cfg.retry_after_s)})
                    continue
                return None, None, (resp.status, body or
                                    {"error": {"message": "replica error",
                                               "type": "server_error"}},
                                    {}), reason, attempts
            return replica, conn, resp, reason, attempts
        return None, None, last_err, "exhausted", attempts

    def _read_error_body(self, resp) -> dict:
        """Read a non-200 response body tolerating a replica that died
        after the status line: the error path must never raise (it would
        crash the handler and defeat the failover it exists for)."""
        try:
            return self._safe_json(resp.read())
        except (http.client.HTTPException, OSError):
            return {}

    @staticmethod
    def _safe_json(raw) -> dict:
        try:
            out = json.loads(raw) if raw else {}
        except (json.JSONDecodeError, UnicodeDecodeError):
            return {}
        return out if isinstance(out, dict) else {}


class _RouterHandler(BaseHTTPRequestHandler):
    router: FleetRouter = None  # bound in serve_router
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _send(self, status: int, payload, ctype: str = "application/json",
              extra_headers: Optional[dict] = None):
        body = payload if isinstance(payload, bytes) \
            else json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        if self.close_connection:
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self) -> tuple[bytes, dict]:
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b""
        try:
            body = json.loads(raw) if raw else {}
        except json.JSONDecodeError:
            return raw, {}
        return raw, (body if isinstance(body, dict) else {})

    def do_GET(self):
        rt = self.router
        url = urllib.parse.urlparse(self.path)
        if url.path == "/healthz":
            return self._send(200, b"ok", "text/plain")
        if url.path == "/readyz":
            # ready = the router can route SOMEWHERE
            if rt.registry.ready():
                return self._send(200, b"ready", "text/plain")
            return self._send(503, b"no ready replicas", "text/plain")
        if url.path == "/metrics" and rt.metrics is not None:
            return self._send(200, rt.metrics.render().encode(),
                              "text/plain; version=0.0.4")
        if url.path == "/metrics/fleet":
            # fleet-merged exposition (ISSUE 20): every replica's full
            # metric snapshot, restart-guard merged at the registry —
            # one scrape target for the whole serving fleet, exemplars
            # preserved
            agg = rt.registry.aggregator
            if agg is None:
                return self._send(404, {"error": "fleet metrics merge "
                                                 "disabled"})
            return self._send(200, agg.render().encode(),
                              "text/plain; version=0.0.4")
        if url.path == "/debug/costs":
            # fleet cost rollup (ISSUE 20): per-(model, pool) and
            # per-tenant spend from the replicas' heartbeat cost
            # snapshots; tools/cost_summary.py renders the headline table
            ledger = rt.registry.cost_ledger
            if ledger is None:
                return self._send(404, {"error": "fleet cost ledger "
                                                 "disabled"})
            snap = ledger.snapshot()
            if rt.registry.aggregator is not None:
                snap["aggregator"] = rt.registry.aggregator.stats()
            return self._send(200, snap)
        if url.path == "/debug/fleet":
            snap = rt.registry.snapshot()
            if rt.directory is not None:
                snap["directory"] = rt.directory.snapshot()
            if rt.scheduler is not None:
                snap["scheduler"] = rt.scheduler.snapshot()
            return self._send(200, snap)
        if url.path == "/debug/scheduler":
            # pool capacity, placements and the throughput matrix
            # (ISSUE 19); tools/fleet_summary.py renders the pool table
            if rt.scheduler is None:
                return self._send(200, {"enabled": False})
            return self._send(200, rt.scheduler.snapshot())
        if url.path == "/debug/traces":
            q = urllib.parse.parse_qs(url.query)
            return self._send(200, rt.tracer.query(
                (q.get("trace_id") or [""])[0]))
        if url.path == "/debug/slo":
            # SLO burn-rate state (ISSUE 17): objectives, per-signal
            # burn, crossing counts and the bounded burn history
            # (tools/slo_summary.py renders timelines from it)
            if rt.slo is None:
                return self._send(200, {"enabled": False})
            return self._send(200, rt.slo.snapshot())
        if url.path == "/v1/models":
            # every replica serves the same base model (+ adapters), so
            # one healthy replica's answer IS the fleet's answer — OpenAI
            # SDK model discovery must work pointed at the router
            tried: set = set()
            for _ in range(max(1, rt.cfg.max_attempts)):
                rep, _reason = rt.pick("", exclude=frozenset(tried))
                if rep is None:
                    break
                tried.add(rep.replica_id)
                try:
                    out = rep.transport.request("GET", "/v1/models",
                                                timeout_s=10.0)
                    return self._send(200, out if isinstance(out, dict)
                                      else {"object": "list", "data": []})
                except (TransportError, CircuitOpenError) as e:
                    log.warning("fleet: /v1/models via %s failed: %s",
                                rep.replica_id, e)
            return self._send(503, {"error": {"message": "no ready replicas",
                                              "type": "overloaded_error"}},
                              extra_headers={"Retry-After":
                                             str(rt.cfg.retry_after_s)})
        self._send(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        rt = self.router
        raw, body = self._read_json()
        if self.path == "/fleet/register":
            try:
                rep = rt.registry.register(
                    str(body.get("replica_id") or ""),
                    str(body.get("base_url") or ""),
                    str(body.get("pod_name") or ""),
                    role=str(body.get("role") or ""),
                    placement_domain=str(body.get("placement_domain")
                                         or ""),
                    generation=str(body.get("generation") or ""),
                    pool=str(body.get("pool") or ""))
            except ValueError as e:
                return self._send(400, {"error": str(e)})
            return self._send(200, {"registered": rep.replica_id,
                                    "role": rep.role})
        if self.path == "/fleet/heartbeat":
            try:
                ok = rt.registry.heartbeat(str(body.get("replica_id") or ""),
                                           body.get("stats") or {},
                                           prefixes=body.get("prefixes"),
                                           metrics_snap=body.get("metrics"),
                                           costs=body.get("costs"))
            except (TypeError, ValueError) as e:
                return self._send(400, {"error": f"bad stats: {e}"})
            # registered:false tells the replica to re-register (evicted,
            # or the router restarted with an empty registry)
            return self._send(200, {"registered": ok})
        if self.path == "/fleet/deregister":
            rt.registry.deregister(str(body.get("replica_id") or ""))
            return self._send(200, {"ok": True})
        if self.path == "/prefix":
            if (rt.cfg.prefix_broadcast or rt.directory is None
                    or not rt.cfg.prefix_directory_enabled):
                return self._broadcast_prefix(body)
            return self._register_prefix(body)
        if self.path not in _FORWARD_ROUTES:
            return self._send(404, {"error": f"no route {self.path}"})
        trace = rt.trace_ctx(self.headers.get("traceparent"))
        # length-bound the tenant at the front door (the serving tier
        # does the same for direct traffic) — cost-ledger cardinality
        # must not be client-controlled beyond the replica's overflow cap
        tenant = str(self.headers.get("X-Tenant") or "")[:64]
        if body.get("stream"):
            return self._relay_stream(self.path, raw, trace, tenant=tenant)
        status, out, headers = rt.forward(self.path, body, trace,
                                          tenant=tenant)
        return self._send(status, out, extra_headers=headers)

    def _register_prefix(self, body: dict):
        """Directory-backed /prefix (ISSUE 16): register the prefix on
        ONE replica (failing over through the ready set) instead of
        fanning out N POSTs. The replica's trie insert publishes the
        prefix to the global directory on its next heartbeat, and every
        other replica adopts the pages lazily — a directory-planned pull
        on its first matching request. The old fan-out stays available
        behind --prefix-broadcast."""
        rt = self.router
        tried: set = set()
        errors: dict = {}
        for _ in range(max(1, rt.cfg.max_attempts)):
            rep, _reason = rt.pick("", exclude=frozenset(tried))
            if rep is None:
                break
            tried.add(rep.replica_id)
            try:
                rep.transport.request("POST", "/prefix", body=body,
                                      timeout_s=rt.cfg.request_timeout_s)
                return self._send(200, {"mode": "directory",
                                        "registered_on": rep.replica_id,
                                        "errors": errors or None})
            except (TransportError, CircuitOpenError) as e:
                errors[rep.replica_id] = str(e)
        if not errors:
            return self._send(503, {"error": "no ready replicas"})
        return self._send(502, {"error": "prefix registration failed on "
                                         "every attempted replica",
                                "errors": errors})

    def _broadcast_prefix(self, body: dict):
        """Prefix registration fans out to EVERY replica: the affinity
        hash may route any given conversation anywhere after membership
        churn, so the shared system prompt must be cached fleet-wide.
        The fan-out is CONCURRENT — one blackholed replica costs one
        timeout total, not a serial timeout per replica (a prefill is
        legitimately slow, so the per-replica budget stays the full
        request timeout)."""
        rt = self.router
        ready = rt.registry.ready()
        results = {}

        def one(rep):
            try:
                rep.transport.request("POST", "/prefix", body=body,
                                      timeout_s=rt.cfg.request_timeout_s)
                results[rep.replica_id] = "ok"
            except (TransportError, CircuitOpenError) as e:
                results[rep.replica_id] = f"error: {e}"

        threads = [threading.Thread(target=one, args=(rep,), daemon=True)
                   for rep in ready]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=rt.cfg.request_timeout_s + 5.0)
        for rep in ready:  # a still-running thread = that replica timed out
            results.setdefault(rep.replica_id, "error: timed out")
        status = 200 if results and all(
            v == "ok" for v in results.values()) else 502
        if not results:
            status = 503
        return self._send(status, {"replicas": results})

    def _relay_stream(self, path: str, raw: bytes, trace: dict,
                      tenant: str = ""):
        rt = self.router
        started = rt.clock()
        body = rt._safe_json(raw)
        key = rt._affinity_key(path, body)
        prefer = None
        # same gate as forward(): embeddings carry no KV to move
        if path != "/v1/embeddings" and rt.disagg_ready():
            prefer = rt.plan_two_hop(path, body, key, trace)
        replica, conn, resp, reason, attempts = rt.open_stream(
            path, raw, trace, prefer=prefer, key=key, tenant=tenant)
        if replica is None:
            status, body, headers = resp
            rt._outcome("rejected" if status in (429, 503) else "failed")
            rt._record_route(trace, path, started, "", status, reason,
                             attempts, True)
            return self._send(status, body,
                              extra_headers={**headers,
                                             "traceparent": trace["header"]})
        self.send_response(200)
        self.send_header("Content-Type",
                         resp.getheader("Content-Type",
                                        "application/octet-stream"))
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("traceparent", trace["header"])
        self.end_headers()
        status, outcome = 200, "ok"
        try:
            try:
                while True:
                    # read1: returns as soon as the replica produced bytes —
                    # the relay must never buffer the whole stream
                    chunk = resp.read1(65536)
                    if not chunk:
                        break
                    self.wfile.write(f"{len(chunk):x}\r\n".encode()
                                     + chunk + b"\r\n")
                    self.wfile.flush()
            except (http.client.HTTPException, OSError):
                # replica died mid-stream: its breaker learns, the client
                # gets a CLEAN truncated stream (terminator below), and
                # the counter records it — a half-relayed generation is
                # not idempotent, so no failover here
                breaker = replica.transport.breaker
                if breaker is not None:
                    breaker.record_failure()
                if rt.metrics is not None:
                    rt.metrics.incr("tpu_fleet_stream_aborted")
                status, outcome = 502, "failed"
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except OSError:
            # OUR client went away mid-relay; nothing to tell it
            status, outcome = 499, "failed"
        finally:
            conn.close()
        rt._outcome(outcome)
        rt._record_route(trace, path, started, replica.replica_id, status,
                         reason, attempts, True)


def serve_router(router: FleetRouter, port: Optional[int] = None
                 ) -> ThreadingHTTPServer:
    handler = type("BoundRouterHandler", (_RouterHandler,),
                   {"router": router})
    httpd = ThreadingHTTPServer(
        ("0.0.0.0", router.cfg.port if port is None else port), handler)
    httpd.daemon_threads = True
    thread = threading.Thread(target=httpd.serve_forever,
                              name="fleet-router", daemon=True)
    thread.start()
    return httpd
