"""Device-native KV page handoff (ISSUE 11): arena-to-arena page movement
for co-located prefill/decode replicas, with ZERO host copies.

The wire path (fleet/handoff.py) moves every page through a device→host
gather, a numpy serialization, an HTTP frame and a host→device scatter —
NIC-order bandwidth on a path the hardware could run at memory-bandwidth
order (the TPU concurrency study in PAPERS.md quantifies the gap:
intra-slice ICI is orders of magnitude above the host/NIC path). This
module is the fast tier above it:

- **Placement domains.** Every replica advertises a *placement domain*
  (``detect_placement_domain``): replicas in the same domain can hand
  device buffers to each other directly. The auto-detected domain is
  ``proc:<host>:<pid>`` — the one co-location this build can PROVE
  supports zero-copy buffer donation (in the fake cloud, every
  FakeWorkerHost replica is a thread of one process, so a whole emulated
  slice shares a domain). Operators with a real same-slice ICI transport
  override it per pool (``TPU_FLEET_PLACEMENT_DOMAIN`` / flag); a domain
  claim the bus can't back simply downgrades to wire — the ladder is
  device → wire → unified fallback, never an error the client sees.

- **DeviceTransferBus.** A process-local registry mapping a replica's
  advertise URL to its live engine + domain. serve_main registers its
  engine at startup (when ``fleet_device_transfer_enabled``); the
  prefill side's ``device_push`` looks the decode replica up by the SAME
  URL the router hands it for the wire push, so the two paths are
  interchangeable per hop.

- **device_push.** The prefill half: same-domain hops run
  ``export_handoff_device`` → ``adopt_handoff_device`` (monolithic) or
  ``export_handoff_stream`` feeding ``adopt_handoff_chunk_device``
  fragments through the decode engine's HandoffStreamAssembler
  (streamed — the PR 10 seq/TTL state machine, just without
  serialize/deserialize in the middle). Page payloads stay device
  arrays end to end: the exporter's jitted gather produces fresh device
  buffers, the adopter's jitted scatter writes them into its arena, and
  refcount/COW accounting moves only after the adoption lands — the
  same all-or-nothing contract the wire path enforces.

Any failure raises ``DeviceTransferError`` (or the engine's
HandoffError); the caller (serve_main's /kv_prefill) counts a downgrade
and falls back to the wire codec unchanged.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import uuid
from typing import Optional

log = logging.getLogger(__name__)


class DeviceTransferError(RuntimeError):
    """A device-path hop that cannot proceed (no bus entry, domain
    mismatch, dead peer). The caller downgrades to the wire codec — this
    is a routing downgrade, never a request failure."""


def detect_placement_domain(override: str = "",
                            env: Optional[dict] = None) -> str:
    """This replica's placement domain: explicit override first (flag >
    TPU_FLEET_PLACEMENT_DOMAIN env), else ``proc:<host>:<pid>`` — the
    co-location the in-process bus can actually serve. Two replicas with
    EQUAL non-empty domains are device-reachable; everything else rides
    the wire."""
    if override:
        return override
    env = os.environ if env is None else env
    from_env = env.get("TPU_FLEET_PLACEMENT_DOMAIN", "")
    if from_env:
        return from_env
    return f"proc:{socket.gethostname()}:{os.getpid()}"


class DeviceTransferBus:
    """Process-local advertise-URL -> (engine, domain) registry. Thread
    safe (handler threads race registration against lookups); entries are
    overwritten on re-registration (a restarted engine under the same
    URL wins)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[str, tuple[object, str]] = {}

    @staticmethod
    def _key(url: str) -> str:
        return (url or "").rstrip("/")

    def register(self, url: str, engine, domain: str) -> None:
        if not url or not domain:
            raise ValueError("device bus registration needs a URL and a "
                             "placement domain")
        with self._lock:
            self._entries[self._key(url)] = (engine, domain)

    def unregister(self, url: str) -> None:
        with self._lock:
            self._entries.pop(self._key(url), None)

    def lookup(self, url: str) -> Optional[tuple[object, str]]:
        with self._lock:
            return self._entries.get(self._key(url))

    def clear(self) -> None:
        """Test hook: the bus is process-global, suites must not leak
        engines between cases."""
        with self._lock:
            self._entries.clear()


# the process-wide bus: serve_main registers engines here; tests register
# theirs directly and clear() between cases
BUS = DeviceTransferBus()


def _streamed_device_push(engine, peer, tokens: list, model: str,
                          window: int) -> dict:
    """The chunked leg of device_push: export_handoff_stream's fragments
    cross to the peer's assembler on a SENDER THREAD behind a bounded
    queue — the same compute/transfer decoupling the wire path's
    serve_main sender has. Adoption takes the PEER's handoff/prefix
    locks (its own admissions hold them through jitted dispatches), so
    running it inline in ``emit`` would stall the prefill compute thread
    mid-hop and give back the overlap the stream exists for; the queue
    bounds fragments in flight exactly like handoff_stream_window does
    for wire frames (fragments pin fresh device buffers, so the bound is
    HBM, not host memory)."""
    import queue as _q

    stream_id = uuid.uuid4().hex
    t = engine.sc.kv_page_tokens
    sendq: "_q.Queue" = _q.Queue(maxsize=max(1, int(window)))
    stats = {"frames": 0, "bytes": 0, "result": None}
    push_err: list = []

    def sender():
        while True:
            frag = sendq.get()
            if frag is None:
                return
            try:
                if frag["final"]:
                    out = peer.adopt_handoff_chunk_device(
                        stream_id, frag["seq"], [], {}, final=True,
                        total_tokens=frag["total_tokens"], model=model)
                else:
                    # pow2-padding trim is a device-side slice — on the
                    # sender thread, never the compute thread
                    n = len(frag["tokens"]) // t
                    sections = {name: a[:, :n]
                                for name, a in frag["sections"].items()}
                    out = peer.adopt_handoff_chunk_device(
                        stream_id, frag["seq"], frag["tokens"], sections,
                        model=model)
                stats["frames"] += 1
                stats["bytes"] += int(out.get("bytes") or 0)
                if out.get("final"):
                    stats["result"] = out
            except Exception as e:  # noqa: BLE001 — any adoption failure
                # aborts the hop; emit sees push_err and stops the export
                push_err.append(e)
                return

    thread = threading.Thread(target=sender, name="kv-device-sender",
                              daemon=True)

    def emit(frag):
        while True:
            if push_err:
                raise DeviceTransferError(
                    f"device stream adoption failed: {push_err[0]}")
            try:
                sendq.put(frag, timeout=0.1)
                return
            except _q.Full:
                continue

    def finish(abort: bool):
        """Land the close sentinel unconditionally (a stranded sender
        would leak a thread per failed hop) — drain stale fragments on
        abort, wait for slots on success (serve_main's finish_sender
        discipline)."""
        if not abort:
            while not push_err:
                try:
                    sendq.put(None, timeout=0.1)
                    thread.join(timeout=120.0)
                    return
                except _q.Full:
                    continue
        while True:
            try:
                sendq.get_nowait()
            except _q.Empty:
                break
        sendq.put(None)
        thread.join(timeout=120.0)

    thread.start()
    try:
        out = engine.export_handoff_stream(tokens, emit)
    except Exception:
        finish(abort=True)
        raise
    finish(abort=False)
    adopted = stats["result"]
    if push_err or adopted is None:
        # the export closed without the peer confirming adoption —
        # treat exactly like an unconfirmed wire push
        raise DeviceTransferError(
            f"device stream closed without a final adoption"
            f"{f': {push_err[0]}' if push_err else ''}")
    # sender-side device accounting (the catalogue's 'sender counts
    # exports': export_handoff_stream is path-agnostic, so the device
    # series moves HERE for streamed hops, mirroring
    # export_handoff_device on the monolithic leg)
    engine.metrics.incr("tpu_serving_kv_handoff_device_runs")
    engine.metrics.incr("tpu_serving_kv_handoff_device_bytes",
                        adopted["bytes"])
    return {"pages": out["pages"], "chunks": out["chunks"],
            "frames": stats["frames"], "bytes": adopted["bytes"],
            "covered_tokens": out["covered_tokens"],
            "matched_tokens": out["matched_tokens"],
            "streamed": True, "adopted": adopted["pages"],
            "path": "device"}


def device_push(engine, target_url: str, tokens: list, *,
                domain: str, bus: Optional[DeviceTransferBus] = None,
                window: int = 8) -> dict:
    """Prefill half of a DEVICE-path handoff: resolve the decode replica
    on the bus, verify co-location, and move the prompt's page run
    arena-to-arena with no serialization. Chunked engines
    (serving_chunk_tokens > 0) stream per-chunk device fragments through
    the decode engine's assembler (strict seq, all-or-nothing adoption)
    with a sender thread overlapping adoption under the next chunk's
    compute (``window`` bounds fragments in flight — serve_main passes
    its handoff_stream_window); monolithic engines move the whole run in
    one export/adopt pair.

    Returns the same shape as the wire hop's reply ({"pages", "bytes",
    "covered_tokens", "matched_tokens"} + streamed/chunks when chunked)
    with ``path: "device"``. Raises DeviceTransferError when the target
    is not device-reachable (caller downgrades to wire) and lets engine
    HandoffErrors propagate (caller downgrades too — mismatched geometry
    or a failed adoption must not kill the request)."""
    bus = bus or BUS
    entry = bus.lookup(target_url)
    if entry is None:
        raise DeviceTransferError(
            f"no device-reachable engine registered at {target_url!r} "
            "(bus miss — replica in another process or not registered)")
    peer, peer_domain = entry
    if not domain or peer_domain != domain:
        raise DeviceTransferError(
            f"placement-domain mismatch: this replica is in {domain!r}, "
            f"{target_url!r} advertises {peer_domain!r}")
    model = engine.cfg.name
    if engine.sc.serving_chunk_tokens > 0:
        return _streamed_device_push(engine, peer, tokens, model, window)
    out = engine.export_handoff_device(tokens)
    adopted = peer.adopt_handoff_device(out["tokens"], out["sections"],
                                        model=model)
    return {"pages": out["pages"], "bytes": adopted["bytes"],
            "covered_tokens": out["covered_tokens"],
            "matched_tokens": out["matched_tokens"],
            "streamed": False, "adopted": adopted["pages"],
            "path": "device"}
