"""Device-native KV page handoff (ISSUE 11): arena-to-arena page movement
for co-located prefill/decode replicas, with ZERO host copies.

The wire path (fleet/handoff.py) moves every page through a device→host
gather, a numpy serialization, an HTTP frame and a host→device scatter —
NIC-order bandwidth on a path the hardware could run at memory-bandwidth
order (the TPU concurrency study in PAPERS.md quantifies the gap:
intra-slice ICI is orders of magnitude above the host/NIC path). This
module is the fast tier above it:

- **Placement domains.** Every replica advertises a *placement domain*
  (``detect_placement_domain``): replicas in the same domain can hand
  device buffers to each other directly. The auto-detected domain is
  ``proc:<host>:<pid>`` — the one co-location this build can PROVE
  supports zero-copy buffer donation (in the fake cloud, every
  FakeWorkerHost replica is a thread of one process, so a whole emulated
  slice shares a domain). Operators with a real same-slice ICI transport
  override it per pool (``TPU_FLEET_PLACEMENT_DOMAIN`` / flag); a domain
  claim the bus can't back simply downgrades to wire — the ladder is
  device → wire → unified fallback, never an error the client sees.

- **DeviceTransferBus.** A process-local registry mapping a replica's
  advertise URL to its live engine + domain. serve_main registers its
  engine at startup (when ``fleet_device_transfer_enabled``); the
  prefill side's ``device_push`` looks the decode replica up by the SAME
  URL the router hands it for the wire push, so the two paths are
  interchangeable per hop.

- **device_push.** The prefill half: same-domain hops run
  ``export_handoff_device`` → ``adopt_handoff_device`` (monolithic) or
  ``export_handoff_stream`` feeding ``adopt_handoff_chunk_device``
  fragments through the decode engine's HandoffStreamAssembler
  (streamed — the PR 10 seq/TTL state machine, just without
  serialize/deserialize in the middle). Page payloads stay device
  arrays end to end: the exporter's jitted gather produces fresh device
  buffers, the adopter's jitted scatter writes them into its arena, and
  refcount/COW accounting moves only after the adoption lands — the
  same all-or-nothing contract the wire path enforces.

Any failure raises ``DeviceTransferError`` (or the engine's
HandoffError); the caller (serve_main's /kv_prefill) counts a downgrade
and falls back to the wire codec unchanged.
"""

from __future__ import annotations

import json as _json
import logging
import mmap
import os
import socket
import tempfile
import threading
import time
import urllib.request
import uuid
from typing import Callable, Optional

log = logging.getLogger(__name__)


class DeviceTransferError(RuntimeError):
    """A device-path hop that cannot proceed (no bus entry, domain
    mismatch, dead peer). The caller downgrades to the wire codec — this
    is a routing downgrade, never a request failure."""


def detect_placement_domain(override: str = "",
                            env: Optional[dict] = None,
                            mode: str = "auto") -> str:
    """This replica's placement domain: explicit override first (flag >
    TPU_FLEET_PLACEMENT_DOMAIN env), then — in ``auto``/``slice`` mode —
    a SLICE-scoped domain derived from the gang/TPU metadata the
    kubelet's gang scheduler stamps on members (``TPU_SLICE_NAME``, the
    same identity gang/env.py renders into the workers' env), else
    ``proc:<host>:<pid>``, the co-location the in-process bus can serve
    with zero configuration. The slice domain is HOST-qualified
    (``slice:<name>:<host>``) because the cross-process rung moves blobs
    through a tmpfs file two processes mmap — same-kernel reachability,
    which a multi-host slice does not give; operators with a real
    inter-host ICI transport override the domain explicitly and take
    responsibility for the claim. ``mode="proc"`` pins the PR 11
    behavior (one process per domain). Two replicas with EQUAL non-empty
    domains are device-reachable; everything else rides the wire."""
    if override:
        return override
    env = os.environ if env is None else env
    from_env = env.get("TPU_FLEET_PLACEMENT_DOMAIN", "")
    if from_env:
        return from_env
    if mode in ("auto", "slice"):
        slice_name = env.get("TPU_SLICE_NAME", "")
        if slice_name:
            return f"slice:{slice_name}:{socket.gethostname()}"
        if mode == "slice":
            log.warning("placement-domain mode 'slice' but TPU_SLICE_NAME "
                        "is unset — falling back to the process domain")
    return f"proc:{socket.gethostname()}:{os.getpid()}"


class DeviceTransferBus:
    """Process-local advertise-URL -> (engine, domain) registry. Thread
    safe (handler threads race registration against lookups); entries are
    overwritten on re-registration (a restarted engine under the same
    URL wins)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[str, tuple[object, str]] = {}

    @staticmethod
    def _key(url: str) -> str:
        return (url or "").rstrip("/")

    def register(self, url: str, engine, domain: str) -> None:
        if not url or not domain:
            raise ValueError("device bus registration needs a URL and a "
                             "placement domain")
        with self._lock:
            self._entries[self._key(url)] = (engine, domain)

    def unregister(self, url: str) -> None:
        with self._lock:
            self._entries.pop(self._key(url), None)

    def lookup(self, url: str) -> Optional[tuple[object, str]]:
        with self._lock:
            return self._entries.get(self._key(url))

    def clear(self) -> None:
        """Test hook: the bus is process-global, suites must not leak
        engines between cases."""
        with self._lock:
            self._entries.clear()


# the process-wide bus: serve_main registers engines here; tests register
# theirs directly and clear() between cases
BUS = DeviceTransferBus()


# -- cross-process same-host rung (ISSUE 16) ----------------------------------
#
# Two replicas in one placement domain but DIFFERENT processes cannot use
# the bus (it holds live engine references). jax 0.4.x has no stable
# cross-process device-transfer API on this toolchain, so the rung between
# "same process" and "wire" is a handoff-codec blob through a tmpfs file:
# the sender writes the serialized run into /dev/shm, the receiver mmaps
# it and adopts through deserialize_pages UNCHANGED (the codec's
# validators work on any buffer — an mmap slices like bytes). No socket
# ever carries the page payload, the receiver's numpy views alias the
# mapped file (zero copies until the arena scatter), and the ladder's
# discipline holds: any failure — missing file, foreign host, torn write,
# refused adoption — downgrades to the wire codec.

_SHM_PREFIX = "tpukv-"


def shm_dir() -> str:
    """Where cross-process blobs live: the kernel tmpfs when the host has
    one (Linux — file bytes stay in page cache, never touch disk), else
    the tmp dir (the rung still works, just through filesystem cache)."""
    d = "/dev/shm"
    return d if os.path.isdir(d) else tempfile.gettempdir()


def write_shm_blob(blob: bytes, dir: Optional[str] = None) -> str:
    """Write one handoff blob to a fresh private file in the shm dir and
    return its path. mkstemp gives an unguessable name with 0600 modes —
    a peer learns the path only from the sender's POST."""
    fd, path = tempfile.mkstemp(prefix=_SHM_PREFIX, suffix=".kv",
                                dir=dir or shm_dir())
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
    except OSError:
        try:
            os.unlink(path)
        except OSError:
            pass
        raise
    return path


def open_shm_blob(path: str, dir: Optional[str] = None) -> mmap.mmap:
    """mmap a peer-written blob read-only. The path is VALIDATED into the
    shm dir with the tpukv- prefix first: the /kv_adopt_shm and /kv_pull
    doors take paths from the network, and without the check they would
    be an open-any-file oracle. Raises DeviceTransferError on a path
    outside the shm dir or a file that cannot map (vanished, torn,
    empty) — the caller downgrades to wire."""
    base = os.path.realpath(dir or shm_dir())
    real = os.path.realpath(str(path or ""))
    if os.path.dirname(real) != base \
            or not os.path.basename(real).startswith(_SHM_PREFIX):
        raise DeviceTransferError(
            f"refusing KV blob path outside {base!r}: {path!r}")
    try:
        with open(real, "rb") as f:
            return mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    except (OSError, ValueError) as e:
        # ValueError = empty file (a torn writer); both downgrade
        raise DeviceTransferError(f"cannot map KV blob {path!r}: {e}") from e


class ShmBlobGC:
    """Owner-side lifecycle for PULL blobs. On the pull path the OWNER
    writes the file and the PULLER unlinks it after adoption (unlink by
    a non-creator is exactly what tmpfs files allow); a puller that dies
    mid-pull would leak the file forever, so the owner tracks what it
    wrote and sweeps anything older than ``ttl_s`` on its next /kv_pull.
    Push-path blobs never come through here — the sender unlinks its own
    file synchronously in a finally. Clock-injected; unlink races with
    the puller are benign (ENOENT = the success path already cleaned
    up)."""

    def __init__(self, ttl_s: float = 120.0,
                 clock: Callable[[], float] = time.monotonic):
        if ttl_s <= 0:
            raise ValueError("ttl_s must be > 0")
        self.ttl_s = ttl_s
        self.clock = clock
        self._lock = threading.Lock()
        self._files: dict[str, float] = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._files)

    def track(self, path: str) -> None:
        with self._lock:
            self._files[path] = self.clock()

    def sweep(self) -> int:
        """Unlink expired blobs; returns how many files actually died
        (a puller-side unlink already having happened is not a leak)."""
        now = self.clock()
        with self._lock:
            expired = [p for p, t in self._files.items()
                       if now - t > self.ttl_s]
            for p in expired:
                del self._files[p]
        n = 0
        for p in expired:
            try:
                os.unlink(p)
                n += 1
            except OSError:
                pass  # the puller unlinked it — the success path
        return n


def _post_json(url: str, payload: dict, timeout_s: float,
               headers: Optional[dict] = None) -> dict:
    """One small JSON POST for the shm control messages (the DATA never
    rides HTTP on this rung — only the path crosses the socket)."""
    req = urllib.request.Request(
        url, data=_json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        raw = resp.read()
    out = _json.loads(raw) if raw else {}
    return out if isinstance(out, dict) else {}


def shm_push(engine, target_url: str, tokens: list, *,
             timeout_s: float = 30.0, dir: Optional[str] = None,
             headers: Optional[dict] = None) -> dict:
    """The cross-process rung of a PUSH hop: export the run through the
    wire codec, park the blob in tmpfs, and hand the target only its
    path (POST /kv_adopt_shm). The target mmaps + adopts with the same
    deserialize_pages validation the wire door runs; the sender unlinks
    the file SYNCHRONOUSLY whether or not adoption landed — the push
    rung never leaves a blob for GC to find. Raises DeviceTransferError
    (caller downgrades to wire) on any refusal."""
    out = engine.export_handoff(tokens)
    blob = out["blob"]
    path = write_shm_blob(blob, dir)
    try:
        try:
            reply = _post_json(target_url.rstrip("/") + "/kv_adopt_shm",
                               {"path": path}, timeout_s, headers)
        except OSError as e:
            raise DeviceTransferError(
                f"shm adoption POST to {target_url!r} failed: {e}") from e
        if not reply.get("ok"):
            raise DeviceTransferError(
                f"shm adoption refused by {target_url!r}: {reply}")
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass
    return {"pages": out["pages"], "bytes": len(blob),
            "covered_tokens": out["covered_tokens"],
            "matched_tokens": out["matched_tokens"],
            "streamed": False, "adopted": reply.get("pages"),
            "path": "shm"}


def device_pull(engine, owner_url: str, tokens: list, *,
                adapter: str = "", domain: str,
                bus: Optional[DeviceTransferBus] = None) -> dict:
    """Device-local rung of a PULL hop (ISSUE 16): the cold replica
    (``engine``) fetches an already-computed page run from the owning
    replica — both in ONE process, resolved on the bus — with zero
    serialization: the owner's export_pull gathers fresh device buffers
    (match-only, it never prefills) and this engine adopts them through
    check_device_sections. KVPullMiss propagates untouched (the owner's
    trie no longer holds the run — every other rung would miss the same
    way, so the caller reports GONE instead of walking the ladder);
    transport-shaped failures raise DeviceTransferError and the caller
    downgrades to the shm/wire pull."""
    bus = bus or BUS
    entry = bus.lookup(owner_url)
    if entry is None:
        raise DeviceTransferError(
            f"no device-reachable engine registered at {owner_url!r} "
            "(bus miss — owner in another process)")
    owner, owner_domain = entry
    if not domain or owner_domain != domain:
        raise DeviceTransferError(
            f"placement-domain mismatch: this replica is in {domain!r}, "
            f"owner {owner_url!r} advertises {owner_domain!r}")
    out = owner.export_pull_device(tokens, adapter=adapter)
    adopted = engine.adopt_handoff_device(out["tokens"], out["sections"],
                                          model=out["model"],
                                          adapter=adapter)
    return {"pages": out["pages"], "bytes": adopted["bytes"],
            "covered_tokens": out["covered_tokens"], "path": "device"}


def _streamed_device_push(engine, peer, tokens: list, model: str,
                          window: int) -> dict:
    """The chunked leg of device_push: export_handoff_stream's fragments
    cross to the peer's assembler on a SENDER THREAD behind a bounded
    queue — the same compute/transfer decoupling the wire path's
    serve_main sender has. Adoption takes the PEER's handoff/prefix
    locks (its own admissions hold them through jitted dispatches), so
    running it inline in ``emit`` would stall the prefill compute thread
    mid-hop and give back the overlap the stream exists for; the queue
    bounds fragments in flight exactly like handoff_stream_window does
    for wire frames (fragments pin fresh device buffers, so the bound is
    HBM, not host memory)."""
    import queue as _q

    stream_id = uuid.uuid4().hex
    t = engine.sc.kv_page_tokens
    sendq: "_q.Queue" = _q.Queue(maxsize=max(1, int(window)))
    stats = {"frames": 0, "bytes": 0, "result": None}
    push_err: list = []

    def sender():
        while True:
            frag = sendq.get()
            if frag is None:
                return
            try:
                if frag["final"]:
                    out = peer.adopt_handoff_chunk_device(
                        stream_id, frag["seq"], [], {}, final=True,
                        total_tokens=frag["total_tokens"], model=model)
                else:
                    # pow2-padding trim is a device-side slice — on the
                    # sender thread, never the compute thread
                    n = len(frag["tokens"]) // t
                    sections = {name: a[:, :n]
                                for name, a in frag["sections"].items()}
                    out = peer.adopt_handoff_chunk_device(
                        stream_id, frag["seq"], frag["tokens"], sections,
                        model=model)
                stats["frames"] += 1
                stats["bytes"] += int(out.get("bytes") or 0)
                if out.get("final"):
                    stats["result"] = out
            except Exception as e:  # noqa: BLE001 — any adoption failure
                # aborts the hop; emit sees push_err and stops the export
                push_err.append(e)
                return

    thread = threading.Thread(target=sender, name="kv-device-sender",
                              daemon=True)

    def emit(frag):
        while True:
            if push_err:
                raise DeviceTransferError(
                    f"device stream adoption failed: {push_err[0]}")
            try:
                sendq.put(frag, timeout=0.1)
                return
            except _q.Full:
                continue

    def finish(abort: bool):
        """Land the close sentinel unconditionally (a stranded sender
        would leak a thread per failed hop) — drain stale fragments on
        abort, wait for slots on success (serve_main's finish_sender
        discipline)."""
        if not abort:
            while not push_err:
                try:
                    sendq.put(None, timeout=0.1)
                    thread.join(timeout=120.0)
                    return
                except _q.Full:
                    continue
        while True:
            try:
                sendq.get_nowait()
            except _q.Empty:
                break
        sendq.put(None)
        thread.join(timeout=120.0)

    thread.start()
    try:
        out = engine.export_handoff_stream(tokens, emit)
    except Exception:
        finish(abort=True)
        raise
    finish(abort=False)
    adopted = stats["result"]
    if push_err or adopted is None:
        # the export closed without the peer confirming adoption —
        # treat exactly like an unconfirmed wire push
        raise DeviceTransferError(
            f"device stream closed without a final adoption"
            f"{f': {push_err[0]}' if push_err else ''}")
    # sender-side device accounting (the catalogue's 'sender counts
    # exports': export_handoff_stream is path-agnostic, so the device
    # series moves HERE for streamed hops, mirroring
    # export_handoff_device on the monolithic leg)
    engine.metrics.incr("tpu_serving_kv_handoff_device_runs")
    engine.metrics.incr("tpu_serving_kv_handoff_device_bytes",
                        adopted["bytes"])
    return {"pages": out["pages"], "chunks": out["chunks"],
            "frames": stats["frames"], "bytes": adopted["bytes"],
            "covered_tokens": out["covered_tokens"],
            "matched_tokens": out["matched_tokens"],
            "streamed": True, "adopted": adopted["pages"],
            "path": "device"}


def device_push(engine, target_url: str, tokens: list, *,
                domain: str, bus: Optional[DeviceTransferBus] = None,
                window: int = 8, target_domain: str = "",
                timeout_s: float = 30.0,
                headers: Optional[dict] = None) -> dict:
    """Prefill half of a DEVICE-path handoff: resolve the decode replica
    on the bus, verify co-location, and move the prompt's page run
    arena-to-arena with no serialization. Chunked engines
    (serving_chunk_tokens > 0) stream per-chunk device fragments through
    the decode engine's assembler (strict seq, all-or-nothing adoption)
    with a sender thread overlapping adoption under the next chunk's
    compute (``window`` bounds fragments in flight — serve_main passes
    its handoff_stream_window); monolithic engines move the whole run in
    one export/adopt pair.

    A bus MISS is no longer the end of the device tier (ISSUE 16): when
    the router vouched the target shares this domain (``target_domain``,
    from its registration data) the hop takes the cross-process shm rung
    — blob through tmpfs, mmap on the far side, zero socket payload.
    Chunked engines skip that rung (a file is inherently monolithic;
    their wire STREAMING overlaps compute with transfer, which the shm
    file cannot) — the full ladder is device-local → shm → wire →
    unified.

    Returns the same shape as the wire hop's reply ({"pages", "bytes",
    "covered_tokens", "matched_tokens"} + streamed/chunks when chunked)
    with ``path: "device"`` (or ``"shm"``). Raises DeviceTransferError
    when the target is not device-reachable (caller downgrades to wire)
    and lets engine HandoffErrors propagate (caller downgrades too —
    mismatched geometry or a failed adoption must not kill the
    request)."""
    bus = bus or BUS
    entry = bus.lookup(target_url)
    if entry is None:
        if domain and target_domain == domain \
                and engine.sc.serving_chunk_tokens <= 0:
            return shm_push(engine, target_url, tokens,
                            timeout_s=timeout_s, headers=headers)
        raise DeviceTransferError(
            f"no device-reachable engine registered at {target_url!r} "
            "(bus miss — replica in another process or not registered"
            + (", streamed hops ride the wire" if domain
               and target_domain == domain else "") + ")")
    peer, peer_domain = entry
    if not domain or peer_domain != domain:
        raise DeviceTransferError(
            f"placement-domain mismatch: this replica is in {domain!r}, "
            f"{target_url!r} advertises {peer_domain!r}")
    model = engine.cfg.name
    if engine.sc.serving_chunk_tokens > 0:
        return _streamed_device_push(engine, peer, tokens, model, window)
    out = engine.export_handoff_device(tokens)
    adopted = peer.adopt_handoff_device(out["tokens"], out["sections"],
                                        model=model)
    return {"pages": out["pages"], "bytes": adopted["bytes"],
            "covered_tokens": out["covered_tokens"],
            "matched_tokens": out["matched_tokens"],
            "streamed": False, "adopted": adopted["pages"],
            "path": "device"}
