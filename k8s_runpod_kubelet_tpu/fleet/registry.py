"""Replica registry: the fleet's live membership + load view.

Serving replicas register and heartbeat with the load stats the router
routes on (free slots, queue depth, KV-cache occupancy, recent TTFT p95 —
all sourced from the surfaces the engine already exports via Metrics and
``/debug/engine``). A replica that stops heartbeating, or whose health
probe fails, is EVICTED — the router must never keep sending traffic to a
corpse on the strength of its last optimistic heartbeat.

Each replica carries its own ``cloud/transport.py`` HttpTransport with a
per-replica CircuitBreaker: one dying replica fails fast (and gets routed
around) without the timeout soak poisoning the other replicas' latency.

Everything is clock-injected (the fleet soak drives eviction, breaker
reset and autoscaler hysteresis from one FakeClock with zero real sleeps).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
import urllib.request
from typing import Callable, Optional

from ..cloud.transport import CircuitBreaker, HttpTransport, OPEN

log = logging.getLogger(__name__)

# replica lifecycle states (the tpu_fleet_replicas{state=...} gauge labels)
READY = "ready"
DRAINING = "draining"
STATES = (READY, DRAINING)

# disaggregated-serving roles (ISSUE 9): a replica registers as one of
# these and the router/autoscaler treat the pools separately — prefill
# replicas compute KV and hand it off, decode replicas adopt KV and
# stream tokens, unified replicas do both (the single-pool default and
# the fallback target when a pool is empty or a handoff fails).
UNIFIED = "unified"
PREFILL = "prefill"
DECODE = "decode"
ROLES = (UNIFIED, PREFILL, DECODE)

# Counters the heartbeat reads CUMULATIVE off the engine's Metrics and a
# registry-tier consumer differences per beat — the SLO tracker's burn
# windows (errors/requests), the scheduler's throughput matrix (decode
# steps), the fleet metrics merge (all of them, via the full snapshot).
# graftlint's merged-counter rule (analysis/checkers/observability.py)
# pins every get_counter literal in this module to this tuple AND to a
# zero-seed site: a counter that starts life mid-flight, or whose merge
# side lacks a RestartGuard, would fabricate fleet deltas on replica
# restart.
GUARDED_HEARTBEAT_COUNTERS = (
    "tpu_serving_prefix_cache_hits",
    "tpu_serving_prefix_cache_misses",
    "tpu_serving_spec_proposed",
    "tpu_serving_spec_accepted",
    "tpu_serving_engine_errors",
    "tpu_serving_prefill_errors",
    "tpu_serving_admitted",
    "tpu_serving_decode_steps",
)

# /debug/costs wire shape (must match workloads/serving/costmeter.py's
# COSTS_SCHEMA_VERSION — stated as a literal here because the fleet tier
# is jax-free by contract and must not import the serving package;
# tests/test_costmeter.py pins the two literals equal)
COSTS_SCHEMA_VERSION = 1


@dataclasses.dataclass
class ReplicaStats:
    """One heartbeat's load snapshot — the router's routing signal.

    Field names match ``/debug/engine`` (debug_snapshot) where a
    counterpart exists; ``ttft_p95_s``/``itl_p95_s`` are computed
    replica-side from the serving histograms' recent tails
    (ReplicaReporter). ``kv_pages_free`` is the arena's reclaimable
    HEADROOM (free + evictable-now trie pages, not the raw free count —
    see ReplicaReporter.stats) over ``kv_pages_total`` — the decode
    pool's scale signal."""

    free_slots: int = 0
    active_slots: int = 0
    max_slots: int = 0
    queue_depth: int = 0
    max_queue_depth: int = 0     # the replica's admission bound (0 = none)
    kv_cache_tokens: int = 0
    ttft_p95_s: float = 0.0
    # role-appropriate load extras (ISSUE 9): decode pools scale on ITL
    # p95 and free KV pages, prefill pools on TTFT/queue (above)
    itl_p95_s: float = 0.0
    kv_pages_free: int = 0
    kv_pages_total: int = 0
    # cumulative completed /kv_prefill hops: the prefill pool's
    # scale-down check watches this ADVANCE between ticks — hops are too
    # short for the sampled inflight count to register steady traffic
    handoffs_total: int = 0
    # cumulative error/request counters for the SLO layer's error-rate
    # burn signal (ISSUE 17): the tracker takes per-beat DELTAS, so these
    # ride the heartbeat as monotonic totals
    errors_total: int = 0
    requests_total: int = 0
    # cumulative decode steps (~ tokens emitted): the fleet scheduler's
    # throughput matrix turns successive beats' deltas into measured
    # tokens/sec-per-chip per generation (ISSUE 19) — same
    # monotonic-total shape as the SLO counters, no new wire protocol
    tokens_total: int = 0
    draining: bool = False

    _FLOATS = ("ttft_p95_s", "itl_p95_s")

    @classmethod
    def from_dict(cls, d: dict) -> "ReplicaStats":
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {}
        for k, v in (d or {}).items():
            if k not in known or v is None:  # nulls fall to field defaults
                continue
            kw[k] = bool(v) if k == "draining" else \
                (float(v) if k in cls._FLOATS else int(v))
        return cls(**kw)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @property
    def saturated(self) -> bool:
        """No free slot AND the admission bound (when one exists) is full:
        a submit forwarded here would 429. With no bound configured a
        replica is never 'saturated' — it queues (the autoscaler's
        signal), it doesn't reject."""
        return (self.free_slots <= 0 and self.max_queue_depth > 0
                and self.queue_depth >= self.max_queue_depth)

    @property
    def load_score(self) -> float:
        """Least-loaded ordering: queued + running work minus headroom.
        Lower routes first; ttft breaks ties in pick()."""
        return float(self.queue_depth + self.active_slots - self.free_slots)


@dataclasses.dataclass
class Replica:
    replica_id: str
    base_url: str
    pod_name: str = ""           # the k8s pod backing it (autoscaler's handle)
    role: str = UNIFIED          # disaggregated pool membership (ISSUE 9)
    # device-native KV transfer (ISSUE 11): replicas advertising EQUAL
    # non-empty placement domains are co-located closely enough to hand
    # device buffers arena-to-arena — the router plans same-domain
    # prefill->decode hops over the device path, everything else rides
    # the wire codec. "" = wire-only (the safe default for replicas that
    # never advertised one).
    placement_domain: str = ""
    # mixed-fleet placement identity (ISSUE 19): which TPU generation the
    # replica runs on and which scheduler node pool reserved its chips.
    # Registration-level like placement_domain — hardware can't change
    # under a live process. "" = unplaced legacy replica (still routable;
    # the scheduler just can't attribute its throughput to a pool).
    generation: str = ""
    pool: str = ""
    state: str = READY
    registered_at: float = 0.0
    last_heartbeat_at: float = 0.0
    stats: ReplicaStats = dataclasses.field(default_factory=ReplicaStats)
    transport: Optional[HttpTransport] = None

    @property
    def breaker_open(self) -> bool:
        return (self.transport is not None
                and self.transport.breaker is not None
                and self.transport.breaker.state == OPEN)

    def to_dict(self, now: float) -> dict:
        return {"replica_id": self.replica_id, "base_url": self.base_url,
                "pod_name": self.pod_name, "role": self.role,
                "placement_domain": self.placement_domain,
                "generation": self.generation, "pool": self.pool,
                "state": self.state,
                "age_s": round(now - self.registered_at, 3),
                "heartbeat_age_s": round(now - self.last_heartbeat_at, 3),
                "breaker_open": self.breaker_open,
                "stats": self.stats.to_dict()}


def _default_probe(replica: Replica, timeout_s: float = 2.0) -> bool:
    """GET /readyz on the replica: 200 = routable. /readyz (not /healthz)
    on purpose — a DRAINING replica answers 503 there while its engine
    thread is still perfectly alive (the serve_main status contract)."""
    try:
        with urllib.request.urlopen(replica.base_url.rstrip("/") + "/readyz",
                                    timeout=timeout_s) as resp:
            return resp.status == 200
    except OSError:
        return False


class ReplicaRegistry:
    """Thread-safe membership map + eviction sweep + fleet gauges.

    ``probe_fn(replica) -> bool`` and ``transport_factory(base_url) ->
    HttpTransport`` are injectable; defaults do real HTTP. ``sweep()`` is
    the eviction tick — router_main runs it on a timer, tests call it
    directly after advancing the injected clock."""

    def __init__(self, metrics=None, tracer=None,
                 clock: Callable[[], float] = time.monotonic,
                 heartbeat_timeout_s: float = 10.0,
                 probe_fn: Optional[Callable[[Replica], bool]] = None,
                 transport_factory=None,
                 breaker_failure_threshold: int = 3,
                 breaker_reset_s: float = 10.0,
                 request_timeout_s: float = 120.0,
                 directory=None, slo=None, scheduler=None,
                 aggregator=None, cost_ledger=None):
        self.metrics = metrics
        self.tracer = tracer
        self.clock = clock
        # fleet metrics merge + cost rollup (ISSUE 20): every accepted
        # heartbeat may carry a full Metrics.snapshot() and a CostMeter
        # snapshot; both are cumulative (idempotent to re-ingest), so
        # they ride every beat with no requeue-on-failure, unlike
        # prefixes. Ingested outside the membership lock like
        # slo/directory/scheduler.
        self.aggregator = aggregator
        self.cost_ledger = cost_ledger
        # fleet scheduler (ISSUE 19): every accepted heartbeat teaches its
        # effective-throughput matrix (tokens/sec-per-chip per generation)
        # — called outside the membership lock like slo/directory
        self.scheduler = scheduler
        # SLO burn-rate tracker (ISSUE 17): every accepted heartbeat is
        # one good/bad observation per signal; membership exits drop the
        # replica's error-counter baseline
        self.slo = slo
        # global prefix directory (ISSUE 16): membership changes and the
        # directory's holder claims move together — evict/deregister/
        # drain drop a replica's claims in the same call, so the router
        # can never plan a pull against a replica the registry just
        # declared dead
        self.directory = directory
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.probe_fn = probe_fn or _default_probe
        self._breaker_failure_threshold = breaker_failure_threshold
        self._breaker_reset_s = breaker_reset_s
        self._request_timeout_s = request_timeout_s
        self._transport_factory = transport_factory or self._make_transport
        self._lock = threading.Lock()
        self._replicas: dict[str, Replica] = {}
        if metrics is not None:
            self._describe(metrics)
            self._update_gauges()

    @staticmethod
    def _describe(m):
        m.describe("tpu_fleet_replicas",
                   "registered serving replicas by lifecycle state "
                   "(labels: state=ready|draining)")
        m.describe("tpu_fleet_registered",
                   "replica registrations accepted")
        m.describe("tpu_fleet_deregistered",
                   "replicas that deregistered cleanly (drain complete)")
        m.describe("tpu_fleet_evictions",
                   "replicas evicted by the registry (labels: reason="
                   "stale|probe|dead)")
        m.describe("tpu_fleet_pool_replicas",
                   "registered replicas per disaggregated-serving pool "
                   "(labels: role=unified|prefill|decode)")

    def _make_transport(self, base_url: str) -> HttpTransport:
        # max_retries=1: same-replica retries are the ROUTER's call (it
        # would rather fail over to a healthy replica than backoff against
        # a sick one); the per-replica breaker still converts a failure
        # streak into fail-fast rejections until its half-open probe heals.
        return HttpTransport(
            base_url, max_retries=1, timeout_s=self._request_timeout_s,
            clock=self.clock,
            breaker=CircuitBreaker(
                failure_threshold=self._breaker_failure_threshold,
                reset_timeout_s=self._breaker_reset_s, clock=self.clock))

    # -- membership ------------------------------------------------------------

    def register(self, replica_id: str, base_url: str,
                 pod_name: str = "", role: str = UNIFIED,
                 placement_domain: str = "", generation: str = "",
                 pool: str = "") -> Replica:
        if not replica_id or not base_url:
            raise ValueError("replica_id and base_url are required")
        role = role or UNIFIED
        if role not in ROLES:
            raise ValueError(f"unknown role {role!r} (one of {ROLES})")
        now = self.clock()
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None or rep.base_url != base_url:
                # fresh transport on a URL change: the old breaker's failure
                # streak belongs to the old address
                rep = Replica(replica_id=replica_id, base_url=base_url,
                              pod_name=pod_name, role=role,
                              registered_at=now,
                              transport=self._transport_factory(base_url))
                self._replicas[replica_id] = rep
            rep.pod_name = pod_name or rep.pod_name
            rep.role = role
            # registration-level (not heartbeat): co-location cannot
            # change without a restart, and a re-registration that stops
            # advertising a domain must drop to wire-only, not keep a
            # stale device claim
            rep.placement_domain = str(placement_domain or "")
            rep.generation = str(generation or "")
            rep.pool = str(pool or "")
            rep.state = READY
            rep.last_heartbeat_at = now
        if self.metrics is not None:
            self.metrics.incr("tpu_fleet_registered")
        self._update_gauges()
        log.info("fleet: replica %s (%s) registered at %s", replica_id,
                 role, base_url)
        return rep

    def heartbeat(self, replica_id: str, stats: dict,
                  prefixes: Optional[list] = None,
                  metrics_snap: Optional[dict] = None,
                  costs: Optional[dict] = None) -> bool:
        """Returns False for an unknown id — the replica should
        re-register (it was evicted, or the router restarted).
        ``prefixes`` is the beat's piggybacked prefix-directory publish
        batch (ISSUE 16) — accepted only from a READY replica; a
        draining one is leaving, so its claims drop instead.
        ``metrics_snap``/``costs`` are the beat's cumulative metric and
        cost snapshots (ISSUE 20) — accepted from draining replicas too:
        their final tokens still cost money."""
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None:
                return False
            rep.stats = ReplicaStats.from_dict(stats)
            rep.last_heartbeat_at = self.clock()
            # DRAINING is STICKY: the engine's drain() is irreversible, so
            # a draining=False heartbeat after mark_draining() is a STALE
            # snapshot (gathered before POST /drain landed) — honoring it
            # would route traffic back to a draining replica for one beat
            # (503s that poison its breaker and trip spurious evictions)
            if rep.stats.draining:
                rep.state = DRAINING
            state = rep.state
            stats_obj = rep.stats
            pod_name, role = rep.pod_name, rep.role
            generation = rep.generation
        if self.scheduler is not None:
            # matrix refinement (ISSUE 19): outside the membership lock
            # like slo/directory — the scheduler has its own lock and a
            # heartbeat must not serialize against place()
            self.scheduler.observe_serving(pod_name or replica_id, role,
                                           generation, stats_obj)
        if self.slo is not None:
            # outside the membership lock: the tracker has its own, and
            # a heartbeat must not serialize against sweep()/ready()
            self.slo.ingest(replica_id, stats_obj)
        if self.directory is not None:
            if state == DRAINING:
                self.directory.drop_replica(replica_id)
            elif prefixes:
                self.directory.publish(replica_id, prefixes)
        if self.aggregator is not None and metrics_snap is not None:
            # own-lock consumer, outside the membership lock; a bad
            # snapshot must not fail the beat (membership > metrics)
            try:
                self.aggregator.ingest(replica_id, metrics_snap)
            except Exception:  # noqa: BLE001
                log.exception("fleet: metrics snapshot from %s rejected",
                              replica_id)
        if self.cost_ledger is not None and costs is not None:
            try:
                self.cost_ledger.ingest(replica_id, costs)
            except Exception:  # noqa: BLE001
                log.exception("fleet: cost snapshot from %s rejected",
                              replica_id)
        self._update_gauges()
        return True

    def mark_draining(self, replica_id: str):
        """Flip a replica to DRAINING ahead of its own heartbeat saying so
        (the autoscaler calls this the moment /drain is accepted, so the
        router stops picking it immediately)."""
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is not None:
                rep.state = DRAINING
        if self.directory is not None:
            # a draining replica is leaving: pulls planned against it
            # would race its exit, so its holder claims drop NOW
            self.directory.drop_replica(replica_id)
        self._update_gauges()

    def registered_pod_names(self) -> set[str]:
        with self._lock:
            return {r.pod_name for r in self._replicas.values() if r.pod_name}

    def deregister(self, replica_id: str) -> bool:
        with self._lock:
            rep = self._replicas.pop(replica_id, None)
        if self.slo is not None:
            self.slo.forget(replica_id)
        if self.aggregator is not None:
            # merged counter/histogram totals SURVIVE the forget — only
            # the replica's gauges and delta baselines drop (ISSUE 20)
            self.aggregator.forget(replica_id)
        if self.cost_ledger is not None:
            self.cost_ledger.forget(replica_id)
        if self.directory is not None:
            self.directory.drop_replica(replica_id)
        if rep is not None and self.metrics is not None:
            self.metrics.incr("tpu_fleet_deregistered")
        self._update_gauges()
        return rep is not None

    def evict(self, replica_id: str, reason: str) -> bool:
        """Remove a replica the fleet has declared dead. ``reason`` feeds
        the eviction counter labels and the fleet.evict span."""
        now = self.clock()
        with self._lock:
            rep = self._replicas.pop(replica_id, None)
        if self.slo is not None:
            self.slo.forget(replica_id)
        if self.aggregator is not None:
            self.aggregator.forget(replica_id)
        if self.cost_ledger is not None:
            self.cost_ledger.forget(replica_id)
        if self.directory is not None:
            # same-transaction consistency (ISSUE 16): the moment the
            # fleet declares a replica dead, its directory claims die
            # too — no pull can be planned against a corpse
            self.directory.drop_replica(replica_id)
        if rep is None:
            return False
        log.warning("fleet: evicting replica %s (%s)", replica_id, reason)
        if self.metrics is not None:
            self.metrics.incr("tpu_fleet_evictions", labels={"reason": reason})
        if self.tracer is not None:
            self.tracer.record("fleet.evict", now, now,
                               attrs={"replica_id": replica_id,
                                      "reason": reason,
                                      "base_url": rep.base_url})
        self._update_gauges()
        return True

    def sweep(self) -> list[str]:
        """Eviction tick: a replica whose heartbeat is stale OR whose
        breaker is open gets ONE health probe; probe failure evicts it.
        (A healthy-but-slow heartbeater survives the probe; a corpse
        doesn't.) Returns the evicted ids."""
        now = self.clock()
        with self._lock:
            suspects = [r for r in self._replicas.values()
                        if (now - r.last_heartbeat_at
                            > self.heartbeat_timeout_s) or r.breaker_open]
        evicted = []
        for rep in suspects:
            stale = now - rep.last_heartbeat_at > self.heartbeat_timeout_s
            try:
                ok = self.probe_fn(rep)
            except Exception as e:  # noqa: BLE001 — a raising probe is a failed probe
                log.info("fleet: probe of %s raised: %s", rep.replica_id, e)
                ok = False
            if not ok:
                if self.evict(rep.replica_id,
                              reason="stale" if stale else "probe"):
                    evicted.append(rep.replica_id)
            elif rep.breaker_open:
                # heal the breaker on probe success: ready() excludes
                # breaker-open replicas, so no request would ever reach
                # allow() (the only lazy OPEN->HALF_OPEN path) — without
                # this a replica that blipped past the threshold would be
                # a permanently unroutable zombie still counted as
                # capacity
                log.info("fleet: probe of %s succeeded; closing its "
                         "breaker", rep.replica_id)
                rep.transport.breaker.record_success()
        return evicted

    # -- reads -----------------------------------------------------------------

    def get(self, replica_id: str) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(replica_id)

    def live(self) -> list[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def ready(self, role: Optional[str] = None) -> list[Replica]:
        """Routable replicas: READY state, breaker not open. ``role``
        filters to one disaggregated pool (None = every pool)."""
        with self._lock:
            return [r for r in self._replicas.values()
                    if r.state == READY and not r.breaker_open
                    and (role is None or r.role == role)]

    def live_role(self, role: str) -> list[Replica]:
        """Every registered replica of one pool, any state — the pool
        autoscaler's membership view."""
        with self._lock:
            return [r for r in self._replicas.values() if r.role == role]

    def snapshot(self) -> dict:
        """The /debug/fleet payload (also what tools/fleet_summary.py
        renders): every replica with its age, role, state and last stats."""
        now = self.clock()
        with self._lock:
            reps = [r.to_dict(now) for r in self._replicas.values()]
        return {"schema_version": 1,
                "replicas": sorted(reps, key=lambda r: r["replica_id"]),
                "ready": sum(1 for r in reps
                             if r["state"] == READY and not r["breaker_open"]),
                "draining": sum(1 for r in reps if r["state"] == DRAINING),
                "pools": {role: sum(1 for r in reps if r["role"] == role)
                          for role in ROLES},
                # mixed-fleet membership (ISSUE 19): replicas per
                # scheduler node pool ("" = legacy/unplaced)
                "node_pools": {pool: sum(1 for r in reps
                                         if r["pool"] == pool)
                               for pool in sorted({r["pool"]
                                                   for r in reps})}}

    def _update_gauges(self):
        if self.metrics is None:
            return
        with self._lock:
            counts = {s: 0 for s in STATES}
            roles = {r: 0 for r in ROLES}
            for r in self._replicas.values():
                counts[r.state] = counts.get(r.state, 0) + 1
                roles[r.role] = roles.get(r.role, 0) + 1
        for state, n in counts.items():
            self.metrics.set_gauge("tpu_fleet_replicas", n,
                                   labels={"state": state})
        for role, n in roles.items():
            self.metrics.set_gauge("tpu_fleet_pool_replicas", n,
                                   labels={"role": role})


_COST_PHASES = ("queue", "prefill", "decode")


def _tot_zero() -> dict:
    return {"requests": 0, "tokens": 0, "prompt_tokens": 0,
            "chip_seconds": {p: 0.0 for p in _COST_PHASES},
            "kv_page_seconds": 0.0, "cost_dollars": 0.0}


def _tot_fold(dst: dict, src: dict) -> None:
    """Fold one cost bucket into another (shape-tolerant: a malformed
    heartbeat contributes zeros, never a KeyError)."""
    dst["requests"] += int(src.get("requests", 0) or 0)
    dst["tokens"] += int(src.get("tokens", 0) or 0)
    dst["prompt_tokens"] += int(src.get("prompt_tokens", 0) or 0)
    cs = src.get("chip_seconds") or {}
    for p in _COST_PHASES:
        dst["chip_seconds"][p] += float(cs.get(p, 0.0) or 0.0)
    dst["kv_page_seconds"] += float(src.get("kv_page_seconds", 0.0) or 0.0)
    dst["cost_dollars"] += float(src.get("cost_dollars", 0.0) or 0.0)


def _group_zero() -> dict:
    g = _tot_zero()
    g.update({"generation": "", "paid_chip_seconds": 0.0,
              "idle_chip_seconds": 0.0, "handoff_bytes": 0, "replicas": 0})
    return g


def _group_fold_snap(group: dict, snap: dict) -> None:
    _tot_fold(group, snap.get("totals") or {})
    group["paid_chip_seconds"] += float(snap.get("paid_chip_seconds", 0.0)
                                        or 0.0)
    group["idle_chip_seconds"] += float(snap.get("idle_chip_seconds", 0.0)
                                        or 0.0)
    group["handoff_bytes"] += int(snap.get("handoff_bytes", 0) or 0)
    group["replicas"] += 1


def _group_fold_group(dst: dict, src: dict) -> None:
    _tot_fold(dst, src)
    dst["paid_chip_seconds"] += src["paid_chip_seconds"]
    dst["idle_chip_seconds"] += src["idle_chip_seconds"]
    dst["handoff_bytes"] += src["handoff_bytes"]
    dst["replicas"] += src["replicas"]
    if not dst["generation"]:
        dst["generation"] = src["generation"]


class FleetCostLedger:
    """Registry-tier cost rollup (ISSUE 20): merges the cumulative
    CostMeter snapshots riding each heartbeat into fleet totals by
    (model, pool) and by tenant — the ``/debug/costs`` payload on the
    router and the input to tools/cost_summary.py.

    Each replica's snapshot is CUMULATIVE since its own start, so the
    merge is last-write-wins per replica; a restart (the snapshot's
    request count going BACKWARDS) and a membership exit both fold the
    superseded snapshot into a retired bucket first — fleet spend never
    un-happens because a replica died. That is the same discipline
    metrics.RestartGuard enforces for merged counters, specialized to
    whole-snapshot epochs."""

    def __init__(self):
        self._lock = threading.Lock()
        # replica_id -> last cost snapshot (current epoch)
        self._live: dict[str, dict] = {}
        # finished epochs, folded by (model, pool) and by tenant
        self._retired_groups: dict[tuple, dict] = {}
        self._retired_tenants: dict[str, dict] = {}
        # replica_id -> unknown schema_version it sent (visible in
        # /debug/costs instead of silently dropping on the floor)
        self._schema_skews: dict[str, object] = {}
        self._ingested = 0

    def ingest(self, replica_id: str, snap) -> None:
        if not isinstance(snap, dict):
            return
        ver = snap.get("schema_version")
        if ver != COSTS_SCHEMA_VERSION:
            with self._lock:
                self._schema_skews[str(replica_id)] = ver
            return
        with self._lock:
            self._ingested += 1
            self._schema_skews.pop(str(replica_id), None)
            prev = self._live.get(replica_id)
            if prev is not None and self._requests(snap) < self._requests(prev):
                # the meter restarted: last-write-wins would erase the
                # old epoch's spend, so retire it first
                self._retire_locked(prev)
            self._live[replica_id] = snap

    @staticmethod
    def _requests(snap: dict) -> int:
        try:
            return int((snap.get("totals") or {}).get("requests", 0))
        except (TypeError, ValueError):
            return 0

    def forget(self, replica_id: str) -> None:
        """Membership exit: the replica's spend moves to the retired
        rollup (fleet totals survive, per-replica detail drops)."""
        with self._lock:
            prev = self._live.pop(replica_id, None)
            self._schema_skews.pop(str(replica_id), None)
            if prev is not None:
                self._retire_locked(prev)

    def _retire_locked(self, snap: dict) -> None:
        key = (str(snap.get("model", "")), str(snap.get("pool", "")))
        group = self._retired_groups.setdefault(key, _group_zero())
        if not group["generation"]:
            group["generation"] = str(snap.get("generation", ""))
        _group_fold_snap(group, snap)
        # retired epochs count capacity, not membership
        group["replicas"] -= 1
        for tenant, bucket in (snap.get("tenants") or {}).items():
            _tot_fold(self._retired_tenants.setdefault(str(tenant),
                                                       _tot_zero()),
                      bucket)

    def snapshot(self) -> dict:
        with self._lock:
            groups: dict[tuple, dict] = {}
            tenants: dict[str, dict] = {}
            for key, g in self._retired_groups.items():
                _group_fold_group(groups.setdefault(key, _group_zero()), g)
            for t, b in self._retired_tenants.items():
                _tot_fold(tenants.setdefault(t, _tot_zero()), b)
            for snap in self._live.values():
                key = (str(snap.get("model", "")), str(snap.get("pool", "")))
                group = groups.setdefault(key, _group_zero())
                if not group["generation"]:
                    group["generation"] = str(snap.get("generation", ""))
                _group_fold_snap(group, snap)
                for t, b in (snap.get("tenants") or {}).items():
                    _tot_fold(tenants.setdefault(str(t), _tot_zero()), b)
            live = {rid: self._live[rid] for rid in sorted(self._live)}
            skews = dict(sorted(self._schema_skews.items()))
            ingested = self._ingested
        out_groups = []
        for (model, pool) in sorted(groups):
            g = groups[(model, pool)]
            paid = g["paid_chip_seconds"]
            spent = sum(g["chip_seconds"].values())
            tokens = g["tokens"]
            out_groups.append({
                "model": model, "pool": pool,
                "generation": g["generation"],
                "replicas": max(0, g["replicas"]),
                "requests": g["requests"],
                "tokens": tokens,
                "prompt_tokens": g["prompt_tokens"],
                "chip_seconds": {p: round(v, 6)
                                 for p, v in g["chip_seconds"].items()},
                "kv_page_seconds": round(g["kv_page_seconds"], 6),
                "cost_dollars": round(g["cost_dollars"], 9),
                "paid_chip_seconds": round(paid, 3),
                "idle_chip_seconds": round(g["idle_chip_seconds"], 3),
                "handoff_bytes": g["handoff_bytes"],
                "utilization": (round(spent / paid, 4)
                                if paid > 0 else None),
                "tokens_per_sec_per_chip": (round(tokens / paid, 4)
                                            if paid > 0 else None),
                "dollars_per_mtok": (round(g["cost_dollars"]
                                           / tokens * 1e6, 6)
                                     if tokens else None),
            })
        out_tenants = {}
        for t in sorted(tenants):
            b = tenants[t]
            out_tenants[t] = {
                "requests": b["requests"], "tokens": b["tokens"],
                "prompt_tokens": b["prompt_tokens"],
                "chip_seconds": {p: round(v, 6)
                                 for p, v in b["chip_seconds"].items()},
                "kv_page_seconds": round(b["kv_page_seconds"], 6),
                "cost_dollars": round(b["cost_dollars"], 9),
                "dollars_per_mtok": (round(b["cost_dollars"]
                                           / b["tokens"] * 1e6, 6)
                                     if b["tokens"] else None),
            }
        return {"schema_version": COSTS_SCHEMA_VERSION,
                "groups": out_groups,
                "tenants": out_tenants,
                "replicas": live,
                "schema_skews": skews,
                "ingested": ingested}


class ReplicaReporter:
    """Replica-side fleet client: register on start, heartbeat on an
    interval with stats from the engine's own debug/metrics surfaces,
    deregister when the drain completes.

    Runs in serve_main when ``--fleet-router`` is set. ``post_fn(path,
    payload) -> dict|None`` is injectable for tests; the default POSTs
    JSON to the router. A router restart answers heartbeats with
    ``registered: false`` and the reporter re-registers — membership
    self-heals without operator action."""

    def __init__(self, engine, router_url: str, replica_id: str,
                 advertise_url: str, pod_name: str = "",
                 interval_s: float = 2.0, post_fn=None,
                 role: str = UNIFIED, placement_domain: str = "",
                 generation: str = "", pool: str = ""):
        self.engine = engine
        self.router_url = router_url.rstrip("/")
        self.replica_id = replica_id
        self.advertise_url = advertise_url
        self.pod_name = pod_name
        self.role = role or UNIFIED
        # device-transfer co-location claim (ISSUE 11); "" = wire-only
        self.placement_domain = placement_domain
        # mixed-fleet identity (ISSUE 19): from TPU_SERVING_GENERATION /
        # TPU_SERVING_POOL stamped by the scheduler-aware pod scaler
        self.generation = generation
        self.pool = pool
        self.interval_s = interval_s
        self._post = post_fn or self._http_post
        self._stop = threading.Event()
        # prefix-directory publish wake (ISSUE 16): the engine's
        # prefix_publish_hook sets this so a fresh trie insert reaches
        # the directory on the NEXT beat, not up to one interval later
        self._wake = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-reporter", daemon=True)

    def wake(self):
        """Engine-side publish hook target: schedule an early beat."""
        self._wake.set()

    def _http_post(self, path: str, payload: dict):
        import json as _json
        req = urllib.request.Request(
            self.router_url + path, data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=5.0) as resp:
            raw = resp.read()
            return _json.loads(raw) if raw else None

    def stats(self) -> dict:
        """The heartbeat payload, from surfaces the engine already exports
        (debug_snapshot + the TTFT histogram's recent tail)."""
        snap = self.engine.debug_snapshot()
        recent = sorted(self.engine.metrics.get_observations(
            "tpu_serving_ttft_seconds")[-100:])
        p95 = recent[max(0, int(len(recent) * 0.95) - 1)] if recent else 0.0
        # ITL p95 (recent tail, like TTFT): the DECODE pool's SLO signal —
        # long prefills inflating inter-token gaps is the interference
        # disaggregation exists to remove, so the autoscaler watches it
        itl = sorted(self.engine.metrics.get_observations(
            "tpu_serving_inter_token_seconds")[-200:])
        itl_p95 = itl[max(0, int(len(itl) * 0.95) - 1)] if itl else 0.0
        pool = snap.get("prefix_cache", {})
        # prefix-cache hit rate (paged KV pool, ISSUE 8): the per-replica
        # signal that shows whether the router's rendezvous prefix-affinity
        # is paying off — fleet_summary.py renders it per replica
        hits = self.engine.metrics.get_counter("tpu_serving_prefix_cache_hits")
        misses = self.engine.metrics.get_counter(
            "tpu_serving_prefix_cache_misses")
        hit_rate = hits / (hits + misses) if hits + misses else 0.0
        # speculative-decode acceptance (ISSUE 14): accepted/proposed draft
        # tokens — per-replica proof the proposer matches its traffic
        # (fleet_summary.py renders it next to prefix%); None when the
        # replica never proposed (speculate_k=0 or all-sampled traffic)
        spec_prop = self.engine.metrics.get_counter(
            "tpu_serving_spec_proposed")
        spec_acc = self.engine.metrics.get_counter(
            "tpu_serving_spec_accepted")
        return {
            "free_slots": snap["max_slots"] - snap["active_slots"],
            "active_slots": snap["active_slots"],
            "max_slots": snap["max_slots"],
            # pending work the ROUTER/autoscaler should see includes
            # requests mid-hop (in_transit) and prefilled-but-not-inserted
            # (ready_queue): a drain-progress check reading queue_depth==0
            # while a request is between queues would delete the pod under
            # it
            "queue_depth": (snap["queue_depth"]
                            + snap.get("in_transit", 0)
                            + snap.get("ready_queue", 0)
                            # in-flight /kv_prefill hops: a prefill-role
                            # replica's whole load lives here (handler
                            # threads, never the scheduler queue) — the
                            # router's load score and the prefill pool's
                            # queue/TTFT-burn signals must see it
                            + snap.get("handoff_inflight", 0)),
            "max_queue_depth": self.engine.sc.max_queue_depth,
            "kv_cache_tokens": snap["kv_cache_tokens"],
            "ttft_p95_s": p95,
            "itl_p95_s": itl_p95,
            # KV headroom for the decode pool's scale signal. Raw free
            # count is the WRONG number: a healthy prefix trie fills the
            # whole arena over time (pages only evict on allocation
            # pressure), so pages_free trends to ~0 at steady state and a
            # naive free/total floor would pin the pool at max. Headroom
            # = free + evictable-NOW (unpinned, trie-only-referenced
            # pages — kv_manager.stats): it only shrinks when live slots
            # and pins genuinely hold residency.
            "kv_pages_free": int(pool.get("pages_free", 0))
            + int(pool.get("pages_evictable", 0)),
            "kv_pages_total": int(pool.get("pages_total", 0)),
            "handoffs_total": snap.get("handoffs_total", 0),
            # cumulative error/request totals for the router's SLO
            # error-rate burn signal (ISSUE 17): the tracker diffs
            # successive beats, so cumulative is the right shape
            "errors_total": (
                self.engine.metrics.get_counter("tpu_serving_engine_errors")
                + self.engine.metrics.get_counter(
                    "tpu_serving_prefill_errors")),
            "requests_total": self.engine.metrics.get_counter(
                "tpu_serving_admitted"),
            # cumulative decode steps ~= tokens emitted: the scheduler's
            # serving-throughput signal (ISSUE 19)
            "tokens_total": self.engine.metrics.get_counter(
                "tpu_serving_decode_steps"),
            "prefix_hit_rate": round(hit_rate, 4),
            "spec_acceptance_rate": (round(spec_acc / spec_prop, 4)
                                     if spec_prop else None),
            "draining": self.engine.draining,
        }

    def register(self):
        self._post("/fleet/register",
                   {"replica_id": self.replica_id,
                    "base_url": self.advertise_url,
                    "pod_name": self.pod_name,
                    "role": self.role,
                    "placement_domain": self.placement_domain,
                    "generation": self.generation,
                    "pool": self.pool})

    def beat_once(self) -> bool:
        """One heartbeat (re-registering if the router forgot us); returns
        False once the reporter deregistered (drain complete)."""
        if self.engine.draining and self.engine.drained:
            try:
                self._post("/fleet/deregister",
                           {"replica_id": self.replica_id})
            except Exception as e:  # noqa: BLE001 — best-effort goodbye
                log.warning("fleet: deregister failed: %s", e)
            return False
        # piggyback pending prefix-directory publishes (ISSUE 16):
        # pending-until-acked — a failed beat puts them back so the
        # directory eventually hears about every inserted run
        take = getattr(self.engine, "take_prefix_publishes", None)
        pubs = take() if take is not None else []
        body = {"replica_id": self.replica_id, "stats": self.stats()}
        if pubs:
            body["prefixes"] = pubs
        # cost attribution plane (ISSUE 20): the full metric snapshot +
        # the cost meter's ledger ride every beat. Both are CUMULATIVE —
        # re-ingesting is idempotent at the registry — so unlike
        # prefixes there is no requeue-on-failure: the next beat's
        # snapshot supersedes this one.
        try:
            body["metrics"] = self.engine.metrics.snapshot()
        except Exception:  # noqa: BLE001 — the beat itself must survive
            log.exception("fleet: metrics snapshot failed")
        costmeter = getattr(self.engine, "costmeter", None)
        if costmeter is not None:
            try:
                body["costs"] = costmeter.snapshot()
            except Exception:  # noqa: BLE001
                log.exception("fleet: cost snapshot failed")
        try:
            out = self._post("/fleet/heartbeat", body)
        except Exception:
            requeue = getattr(self.engine, "requeue_prefix_publishes", None)
            if pubs and requeue is not None:
                requeue(pubs)
            raise
        if isinstance(out, dict) and out.get("registered") is False:
            self.register()
        return True

    def _loop(self):
        while not self._stop.is_set():
            try:
                if not self.beat_once():
                    return
            except Exception as e:  # noqa: BLE001 — router may be briefly down
                log.warning("fleet: heartbeat to %s failed: %s",
                            self.router_url, e)
            # interval sleep, interruptible by stop() AND by the engine's
            # publish hook (wake()) so fresh prefixes beat immediately
            self._wake.wait(self.interval_s)
            self._wake.clear()

    def start(self) -> "ReplicaReporter":
        try:
            self.register()
        except Exception as e:  # noqa: BLE001 — the loop keeps retrying
            log.warning("fleet: initial register failed "
                        "(heartbeats will retry): %s", e)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._wake.set()  # break the interval wait immediately
        if self._thread.is_alive():
            self._thread.join(timeout=5)
