"""Heterogeneity- and goodput-aware fleet scheduler over mixed TPU pools.

ROADMAP item 2 (ISSUE 19). The kubelet stops pretending the fleet is one
homogeneous node: the operator declares **node pools** — per-generation
chip counts (``fleet_pools="v5e:32,v5p:64"``) priced and roofline-rated
by the shared generations table — and every capacity request (serving
scale-ups via the pool autoscalers, training gangs, best-effort packing)
flows through ``place()``, which maximizes **goodput-per-dollar**:

- the scheduler keeps an **effective-throughput matrix** per (workload
  kind x generation), seeded from the roofline the disagg split exposes —
  prefill is FLOPs-bound, decode is HBM-bandwidth-bound, training tracks
  FLOPs x target-MFU — and refined online from the fleet's own telemetry
  (tokens/sec-per-chip out of serving heartbeats, measured MFU out of the
  kubelet's TPU_TELEMETRY scrape). No new wire protocol: both signals
  already flow (registry heartbeats, training_watch scrapes).
- placement picks the pool with the best ``effective-throughput / $``
  among those with room, Gavel-style ("Heterogeneity-Aware Cluster
  Scheduling Policies", PAPERS.md) — under contention the 1.5x per-dollar
  prefill advantage of a v5e beats its 1.04x decode advantage, so
  prefill lands on the FLOPs-per-dollar pool and decode takes the
  bandwidth-rich one.
- **best-effort training** packs onto chips the serving autoscalers
  aren't using and is the preemption buffer: when a non-best-effort
  request finds its pool full, victims are evicted
  **lowest-goodput-loss-first**, where loss is the PR 5/6 ledger's
  unsaved work since the last durable checkpoint (goodput-weighted
  chip-seconds that preemption would destroy).

Everything is injected-clock and lock-disciplined like the rest of the
fleet tier; the deterministic scheduler soak drives it from a FakeClock
with a seeded FaultPlan. A ``round_robin`` policy ships alongside for the
bench's like-for-like goodput-per-dollar comparison (``bench.py
--scheduler``).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Optional

from ..generations import GENERATIONS, GenerationSpec, generation_of

log = logging.getLogger(__name__)

# workload kinds the throughput matrix is indexed by. Serving kinds match
# the registry's pool roles; "training" covers gangs (best-effort is a
# training placement with the preemptible flag, not a separate kind).
PREFILL = "prefill"
DECODE = "decode"
UNIFIED = "unified"
TRAINING = "training"
WORKLOAD_KINDS = (PREFILL, DECODE, UNIFIED, TRAINING)

HETERO = "hetero"
ROUND_ROBIN = "round_robin"
POLICIES = (HETERO, ROUND_ROBIN)

# matrix seed for training: a healthy gang runs at roughly this MFU
# (bench.py's _TARGET_MFU) until a measured value replaces the guess
_SEED_TRAINING_MFU = 0.4


class PoolSpecError(ValueError):
    """A fleet_pools spec that cannot be parsed or priced."""


@dataclasses.dataclass(frozen=True)
class NodePool:
    """One homogeneous slab of capacity: a generation and a chip count.

    ``name`` defaults to the generation but an operator can run two pools
    of one generation (``"edge=v5e:16,bulk=v5e:64"``) — e.g. different
    zones or reservations — and place onto them separately."""

    name: str
    generation: str
    total_chips: int

    @property
    def spec(self) -> GenerationSpec:
        return GENERATIONS[self.generation]


def parse_pools(spec: str) -> list[NodePool]:
    """``"v5e:32,v5p:64"`` (or ``"name=v5e:32"``) -> NodePool list.

    The generation must be a row of the shared generations table — an
    unpriced pool can't be placed onto by goodput-per-dollar."""
    pools: list[NodePool] = []
    seen: set[str] = set()
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, rest = part.partition("=")
        if not rest:
            name, rest = "", name
        gen, _, chips_s = rest.partition(":")
        gen = gen.strip().lower()
        name = (name.strip() or gen)
        if gen not in GENERATIONS:
            raise PoolSpecError(
                f"pool {part!r}: unknown generation {gen!r} "
                f"(one of {sorted(GENERATIONS)})")
        try:
            chips = int(chips_s)
        except ValueError:
            raise PoolSpecError(
                f"pool {part!r}: chip count {chips_s!r} is not an int")
        if chips <= 0:
            raise PoolSpecError(f"pool {part!r}: chip count must be > 0")
        if name in seen:
            raise PoolSpecError(f"duplicate pool name {name!r}")
        seen.add(name)
        pools.append(NodePool(name=name, generation=gen, total_chips=chips))
    return pools


@dataclasses.dataclass
class Placement:
    """One granted reservation: ``tag`` is the caller's handle (the pod
    name for serving replicas and training gangs) and the release key."""

    tag: str
    kind: str
    pool: str
    generation: str
    chips: int
    best_effort: bool = False
    reason: str = ""
    placed_at: float = 0.0
    # preemption-cost estimate for best-effort placements: unsaved work
    # since the last durable checkpoint in goodput-weighted chip-seconds
    # (the PR 5/6 ledger's unsaved_work_s x goodput x chips), refreshed
    # by observe_training. Lowest loss is preempted first.
    goodput_loss: float = 0.0

    def to_dict(self) -> dict:
        return {"tag": self.tag, "kind": self.kind, "pool": self.pool,
                "generation": self.generation, "chips": self.chips,
                "best_effort": self.best_effort,
                "goodput_loss": round(self.goodput_loss, 3),
                "reason": self.reason}


class ThroughputMatrix:
    """Effective throughput per (workload kind x generation).

    Seeded from the roofline — prefill/training follow peak bf16 TFLOP/s,
    decode follows peak HBM GB/s, unified the geometric mean of both (it
    does each half of the request) — and refined online with an EWMA of
    measured values. A generation nobody has measured yet borrows the
    best-measured sibling's value scaled by the ROOFLINE RATIO (Gavel's
    trick: relative throughput transfers across hardware long before
    absolute numbers are known everywhere).

    Units per kind are arbitrary but consistent across generations
    (placement only compares ratios), so roofline seeds and measured
    tokens/sec-per-chip (serving) or effective TFLOP/s (training) mix."""

    def __init__(self, ewma_alpha: float = 0.25):
        if not 0 < ewma_alpha <= 1:
            raise ValueError("ewma_alpha must be in (0, 1]")
        self.alpha = ewma_alpha
        self._lock = threading.Lock()
        # (kind, generation) -> (ewma value, observation count)
        self._measured: dict[tuple[str, str], tuple[float, int]] = {}

    @staticmethod
    def roofline(kind: str, generation: str) -> float:
        spec = GENERATIONS[generation_of(generation)]
        if kind == DECODE:
            return spec.peak_hbm_gbps
        if kind == UNIFIED:
            return (spec.peak_tflops_bf16 * spec.peak_hbm_gbps) ** 0.5
        if kind == TRAINING:
            return spec.peak_tflops_bf16 * _SEED_TRAINING_MFU
        return spec.peak_tflops_bf16  # PREFILL (and any unknown kind)

    def observe(self, kind: str, generation: str, value: float):
        """Fold one measured throughput sample (workload-native units,
        e.g. tokens/sec-per-chip or achieved TFLOP/s) into the EWMA."""
        if value <= 0:
            return
        generation = generation_of(generation)
        key = (kind, generation)
        with self._lock:
            prev = self._measured.get(key)
            if prev is None:
                self._measured[key] = (value, 1)
            else:
                ewma, n = prev
                self._measured[key] = (
                    ewma + self.alpha * (value - ewma), n + 1)

    def effective(self, kind: str, generation: str) -> float:
        """Best current estimate for (kind, generation): measured EWMA,
        else the best-measured sibling scaled by roofline ratio, else the
        roofline seed itself."""
        generation = generation_of(generation)
        with self._lock:
            hit = self._measured.get((kind, generation))
            if hit is not None:
                return hit[0]
            # sibling transfer: most-observed first, name tie-break for
            # determinism
            siblings = [(n, g, v) for (k, g), (v, n)
                        in self._measured.items() if k == kind]
        if siblings:
            _, sib_gen, sib_val = max(
                siblings, key=lambda s: (s[0], s[1]))
            ratio = (self.roofline(kind, generation)
                     / self.roofline(kind, sib_gen))
            return sib_val * ratio
        return self.roofline(kind, generation)

    def snapshot(self) -> dict:
        """``{kind: {generation: {eff, measured, samples}}}`` across the
        declared generations — the /debug and fleet_summary surface."""
        with self._lock:
            measured = dict(self._measured)
        out: dict = {}
        for kind in WORKLOAD_KINDS:
            row = {}
            for gen in GENERATIONS:
                hit = measured.get((kind, gen))
                row[gen] = {"eff": round(self.effective(kind, gen), 3),
                            "measured": hit is not None,
                            "samples": hit[1] if hit else 0}
            out[kind] = row
        return out


class FleetScheduler:
    """Pool-aware placement maximizing goodput-per-dollar.

    ``place()/release()`` are the only capacity-mutating entry points —
    the per-pool serving autoscalers request chips here instead of
    creating pods directly, training gang translation honors the
    resulting ``tpu.dev/pool`` annotation, and a restarted control plane
    rebuilds its reservations from those annotations via ``adopt()``
    (placement must survive the scheduler's death without double-placing
    a pod that already exists).

    ``preempt_fn(placement)`` is the eviction side-effect hook (delete
    the pod / requeue the gang); the scheduler only picks victims and
    frees their chips."""

    def __init__(self, pools, metrics=None, tracer=None,
                 clock: Callable[[], float] = time.monotonic,
                 policy: str = HETERO,
                 preempt_fn: Optional[Callable[[Placement], None]] = None,
                 matrix: Optional[ThroughputMatrix] = None,
                 default_serving_chips: int = 8):
        if isinstance(pools, str):
            pools = parse_pools(pools)
        if not pools:
            raise PoolSpecError("a scheduler needs at least one pool")
        if policy not in POLICIES:
            raise ValueError(f"unknown policy {policy!r} (one of {POLICIES})")
        self.pools: dict[str, NodePool] = {p.name: p for p in pools}
        self._pool_order = [p.name for p in pools]  # spec order, for RR
        self.policy = policy
        self.metrics = metrics
        self.tracer = tracer
        self.clock = clock
        self.matrix = matrix or ThroughputMatrix()
        self.preempt_fn = preempt_fn
        self.default_serving_chips = default_serving_chips
        self._lock = threading.Lock()
        self._placements: dict[str, Placement] = {}
        self._rr_next = 0
        # per-replica (tokens_total, at) baselines for the serving
        # throughput refinement — keyed by pod_name like placements
        self._token_baseline: dict[str, tuple[int, float]] = {}
        if metrics is not None:
            self._describe(metrics)
            self._update_gauges()

    @staticmethod
    def _describe(m):
        m.describe("tpu_fleet_pool_chips",
                   "node-pool chip accounting (labels: pool=, "
                   "state=free|reserved)")
        m.describe("tpu_fleet_pool_placements",
                   "placements granted per pool (labels: pool=, kind=, "
                   "best_effort=true|false)")
        m.describe("tpu_fleet_pool_rejections",
                   "place() requests no pool had room for (labels: kind=)")
        m.describe("tpu_fleet_preemptions",
                   "best-effort placements evicted to make room (labels: "
                   "reason=goodput)")

    # -- scoring ---------------------------------------------------------------

    def _reserved(self, pool_name: str) -> int:
        return sum(p.chips for p in self._placements.values()
                   if p.pool == pool_name)

    def free_chips(self, pool_name: str) -> int:
        with self._lock:
            return (self.pools[pool_name].total_chips
                    - self._reserved(pool_name))

    def _score(self, kind: str, pool: NodePool) -> float:
        """Goodput-per-dollar: effective throughput per chip over
        $/chip-hr (chip counts cancel)."""
        return (self.matrix.effective(kind, pool.generation)
                / pool.spec.cost_per_chip_hr)

    def _rank(self, kind: str) -> list[tuple[float, NodePool]]:
        """Pools best-first by per-dollar score; name tie-break."""
        scored = [(self._score(kind, p), p) for p in self.pools.values()]
        scored.sort(key=lambda sp: (-sp[0], sp[1].name))
        return scored

    @staticmethod
    def _cite(kind: str, chosen: NodePool,
              ranked: list[tuple[float, NodePool]]) -> str:
        """The human-readable scale-event reason: the chosen pool's
        per-dollar score and the alternatives it beat (or lost to on
        capacity)."""
        parts = []
        for score, pool in ranked:
            mark = "->" if pool.name == chosen.name else "  "
            parts.append(f"{mark}{pool.name}({pool.generation}) "
                         f"{score:.1f}/$ ")
        return (f"{kind} per-dollar ranking: "
                + "".join(parts).rstrip())

    # -- placement -------------------------------------------------------------

    def place(self, kind: str, chips: int, tag: str,
              best_effort: bool = False) -> Optional[Placement]:
        """Reserve ``chips`` for ``tag``; None when no pool has room (and
        preemption couldn't make any — callers must treat that as
        capacity exhaustion, not an error). Idempotent per tag: a retry
        after a crash gets the existing reservation back instead of
        double-placing."""
        if kind not in WORKLOAD_KINDS:
            raise ValueError(f"unknown kind {kind!r} "
                             f"(one of {WORKLOAD_KINDS})")
        if chips <= 0:
            raise ValueError("chips must be > 0")
        if not tag:
            raise ValueError("a placement needs a tag")
        now = self.clock()
        victims: list[Placement] = []
        with self._lock:
            existing = self._placements.get(tag)
            if existing is not None:
                return existing
            placement = self._place_locked(kind, chips, tag, best_effort,
                                           now, victims)
        # side effects outside the lock: preemption callbacks do pod
        # deletes (HTTP), and gauges/spans take their own locks
        for victim in victims:
            self._record_preemption(victim, for_tag=tag, now=now)
        if placement is None:
            if self.metrics is not None:
                self.metrics.incr("tpu_fleet_pool_rejections",
                                  labels={"kind": kind})
            self._span(now, action="no_capacity", kind=kind, chips=chips,
                       tag=tag)
            log.warning("fleet-scheduler: no pool has %d chips for %s %s",
                        chips, kind, tag)
            return None
        if self.metrics is not None:
            self.metrics.incr(
                "tpu_fleet_pool_placements",
                labels={"pool": placement.pool, "kind": kind,
                        "best_effort": str(best_effort).lower()})
        self._update_gauges()
        self._span(now, action="place", kind=kind, chips=chips, tag=tag,
                   pool=placement.pool, generation=placement.generation,
                   best_effort=best_effort, reason=placement.reason)
        log.info("fleet-scheduler: %s", placement.reason)
        return placement

    def _place_locked(self, kind, chips, tag, best_effort, now,
                      victims: list) -> Optional[Placement]:
        ranked = self._rank(kind)
        if self.policy == ROUND_ROBIN:
            order = [self.pools[self._pool_order[
                (self._rr_next + i) % len(self._pool_order)]]
                for i in range(len(self._pool_order))]
            chosen = next((p for p in order
                           if self.pools[p.name].total_chips
                           - self._reserved(p.name) >= chips), None)
            if chosen is None:
                return None
            self._rr_next = (self._pool_order.index(chosen.name) + 1) \
                % len(self._pool_order)
            reason = (f"{kind}@{chips} -> pool {chosen.name} "
                      f"(round-robin, heterogeneity-blind)")
            return self._grant(kind, chips, tag, best_effort, chosen,
                               reason, now)
        for score, pool in ranked:
            free = pool.total_chips - self._reserved(pool.name)
            if free >= chips:
                reason = (f"{kind}@{chips} -> pool {pool.name} "
                          f"({pool.generation}, "
                          f"eff {self.matrix.effective(kind, pool.generation):.1f}/chip"
                          f" / ${pool.spec.cost_per_chip_hr:.2f}/chip-hr"
                          f" = {score:.1f}/$); "
                          + self._cite(kind, pool, ranked))
                return self._grant(kind, chips, tag, best_effort, pool,
                                   reason, now)
            if best_effort:
                continue  # best-effort never preempts anyone
            # capacity crunch: can evicting best-effort work make room in
            # this (the best-scoring) pool? Victims leave
            # lowest-goodput-loss-first — the cheapest unsaved work dies
            # first.
            preemptible = sorted(
                (p for p in self._placements.values()
                 if p.pool == pool.name and p.best_effort),
                key=lambda p: (p.goodput_loss, p.tag))
            reclaim, chosen_victims = free, []
            for victim in preemptible:
                if reclaim >= chips:
                    break
                reclaim += victim.chips
                chosen_victims.append(victim)
            if reclaim < chips:
                continue  # even preemption can't fit it here; next pool
            for victim in chosen_victims:
                del self._placements[victim.tag]
                victims.append(victim)
            reason = (f"{kind}@{chips} -> pool {pool.name} "
                      f"({pool.generation}, {score:.1f}/$) after "
                      f"preempting {len(chosen_victims)} best-effort "
                      f"placement(s), lowest goodput-loss first; "
                      + self._cite(kind, pool, ranked))
            return self._grant(kind, chips, tag, best_effort, pool,
                               reason, now)
        return None

    def _grant(self, kind, chips, tag, best_effort, pool: NodePool,
               reason: str, now: float) -> Placement:
        placement = Placement(tag=tag, kind=kind, pool=pool.name,
                              generation=pool.generation, chips=chips,
                              best_effort=best_effort, reason=reason,
                              placed_at=now)
        self._placements[tag] = placement
        return placement

    def _record_preemption(self, victim: Placement, for_tag: str,
                           now: float):
        log.warning("fleet-scheduler: preempting best-effort %s "
                    "(goodput loss %.1f chip-s) for %s",
                    victim.tag, victim.goodput_loss, for_tag)
        if self.metrics is not None:
            self.metrics.incr("tpu_fleet_preemptions",
                              labels={"reason": "goodput"})
        self._span(now, action="preempt", kind=victim.kind,
                   chips=victim.chips, tag=victim.tag, pool=victim.pool,
                   generation=victim.generation,
                   reason=f"preempted for {for_tag}; unsaved work "
                          f"{victim.goodput_loss:.1f} chip-s was the "
                          f"lowest in pool")
        if self.preempt_fn is not None:
            try:
                self.preempt_fn(victim)
            except Exception:  # noqa: BLE001 — eviction hooks must not kill placement
                log.exception("fleet-scheduler: preempt_fn failed for %s",
                              victim.tag)

    def release(self, tag: str, reason: str = "released") -> bool:
        """Free a reservation (pod deleted, gang finished). Unknown tags
        are fine — release is the cleanup path and must be idempotent."""
        now = self.clock()
        with self._lock:
            placement = self._placements.pop(tag, None)
        self._token_baseline.pop(tag, None)
        if placement is None:
            return False
        self._update_gauges()
        self._span(now, action="release", kind=placement.kind,
                   chips=placement.chips, tag=tag, pool=placement.pool,
                   generation=placement.generation, reason=reason)
        return True

    def adopt(self, pods: list) -> int:
        """Rebuild reservations from live pods' ``tpu.dev/pool``
        annotations after a restart. A pod already placed is skipped
        (idempotent), an unknown pool is logged and skipped (the operator
        shrank the spec under running pods — don't guess). Returns the
        number of placements adopted."""
        from ..provider.annotations import Annotations as A
        adopted = 0
        now = self.clock()
        for pod in pods or []:
            meta = pod.get("metadata", {})
            anns = meta.get("annotations", {}) or {}
            pool_name = anns.get(A.POOL)
            if not pool_name:
                continue
            tag = meta.get("name", "")
            if pool_name not in self.pools:
                log.warning("fleet-scheduler: pod %s names unknown pool "
                            "%s; not adopting", tag, pool_name)
                continue
            kind = anns.get(A.POOL_KIND) or UNIFIED
            if kind not in WORKLOAD_KINDS:
                kind = UNIFIED
            chips = _pod_chips(pod)
            best_effort = (anns.get(A.BEST_EFFORT, "")
                           .lower() in ("1", "true", "yes"))
            with self._lock:
                if tag in self._placements:
                    continue
                pool = self.pools[pool_name]
                self._grant(kind, chips, tag, best_effort, pool,
                            f"adopted from pod {tag} annotations "
                            f"after restart", now)
            adopted += 1
            self._span(now, action="adopt", kind=kind, chips=chips,
                       tag=tag, pool=pool_name,
                       generation=self.pools[pool_name].generation)
        if adopted:
            self._update_gauges()
            log.info("fleet-scheduler: adopted %d placement(s) from pod "
                     "annotations", adopted)
        return adopted

    # -- telemetry refinement --------------------------------------------------

    def observe_serving(self, pod_name: str, role: str, generation: str,
                        stats, now: Optional[float] = None):
        """Refine the serving columns from a replica heartbeat the
        registry already receives: tokens/sec-per-chip from the
        cumulative ``tokens_total`` counter's delta. Replicas the
        scheduler didn't place (legacy fleets) still teach the matrix —
        chips fall back to the autoscaler's per-replica default."""
        tokens = int(getattr(stats, "tokens_total", 0) or 0)
        if not pod_name or tokens <= 0:
            return
        now = self.clock() if now is None else now
        kind = role if role in WORKLOAD_KINDS else UNIFIED
        with self._lock:
            placement = self._placements.get(pod_name)
            chips = placement.chips if placement is not None \
                else self.default_serving_chips
            if placement is not None:
                generation = placement.generation
            baseline = self._token_baseline.get(pod_name)
            self._token_baseline[pod_name] = (tokens, now)
        if not generation:
            return  # nothing to attribute the throughput to
        if baseline is None:
            return  # first sighting sets the baseline, not a rate
        last_tokens, last_at = baseline
        dt = now - last_at
        if dt <= 0 or tokens < last_tokens:  # restart reset the counter
            return
        rate_per_chip = (tokens - last_tokens) / dt / max(1, chips)
        if rate_per_chip > 0:
            self.matrix.observe(kind, generation, rate_per_chip)

    def observe_training(self, tag: str, generation: str = "",
                         mfu: float = 0.0, goodput: float = 1.0,
                         unsaved_work_s: Optional[float] = None):
        """Refine the training column (+ the placement's preemption-cost
        estimate) from the kubelet's existing TPU_TELEMETRY scrape.
        ``unsaved_work_s`` is the ledger's productive time since the last
        durable checkpoint — goodput-weighted and chip-scaled it becomes
        the loss preemption would cause."""
        with self._lock:
            placement = self._placements.get(tag)
            if placement is not None:
                generation = placement.generation
                if unsaved_work_s is not None:
                    placement.goodput_loss = (max(0.0, unsaved_work_s)
                                              * max(0.0, goodput)
                                              * placement.chips)
        if generation and mfu > 0:
            spec = GENERATIONS[generation_of(generation)]
            self.matrix.observe(TRAINING, generation,
                                mfu * spec.peak_tflops_bf16)

    # -- read surfaces ---------------------------------------------------------

    def placements(self) -> list[Placement]:
        with self._lock:
            return sorted(self._placements.values(), key=lambda p: p.tag)

    def rates(self) -> tuple[float, float]:
        """(goodput rate, cost rate) of the CURRENT reservations:
        effective throughput summed over placements, and $/hr burned.
        Integrated over a trace this is the bench's goodput-per-dollar."""
        with self._lock:
            placements = list(self._placements.values())
        goodput = sum(self.matrix.effective(p.kind, p.generation) * p.chips
                      for p in placements)
        cost = sum(GENERATIONS[p.generation].cost_per_chip_hr * p.chips
                   for p in placements)
        return goodput, cost

    def snapshot(self) -> dict:
        """The /debug/scheduler + fleet_summary surface."""
        with self._lock:
            placements = sorted(self._placements.values(),
                                key=lambda p: p.tag)
            pools = []
            for name in self._pool_order:
                pool = self.pools[name]
                reserved = self._reserved(name)
                pools.append({
                    "pool": name, "generation": pool.generation,
                    "total_chips": pool.total_chips,
                    "reserved_chips": reserved,
                    "free_chips": pool.total_chips - reserved,
                    "cost_per_chip_hr": pool.spec.cost_per_chip_hr})
        return {"policy": self.policy, "pools": pools,
                "placements": [p.to_dict() for p in placements],
                "matrix": self.matrix.snapshot()}

    # -- plumbing --------------------------------------------------------------

    def _update_gauges(self):
        if self.metrics is None:
            return
        with self._lock:
            per_pool = [(name, self.pools[name].total_chips,
                         self._reserved(name))
                        for name in self._pool_order]
        for name, total, reserved in per_pool:
            self.metrics.set_gauge("tpu_fleet_pool_chips", reserved,
                                   labels={"pool": name,
                                           "state": "reserved"})
            self.metrics.set_gauge("tpu_fleet_pool_chips", total - reserved,
                                   labels={"pool": name, "state": "free"})

    def _span(self, now: float, action: str, kind: str, chips: int,
              tag: str, pool: str = "", generation: str = "",
              best_effort: bool = False, reason: str = ""):
        if self.tracer is None:
            return
        self.tracer.record("fleet.schedule", now, now,
                           attrs={"action": action, "kind": kind,
                                  "chips": chips, "tag": tag,
                                  "pool": pool, "generation": generation,
                                  "best_effort": best_effort,
                                  "reason": reason})


def _pod_chips(pod: dict) -> int:
    total = 0
    for container in pod.get("spec", {}).get("containers", []):
        limits = container.get("resources", {}).get("limits", {})
        try:
            total += int(limits.get("google.com/tpu", 0))
        except (TypeError, ValueError):
            pass
    return max(1, total)
