"""Sparse Mixture-of-Experts MLP (Mixtral-style top-k routing), TPU-first.

Reference parity note: the reference (BSVogler/k8s-runpod-kubelet) contains no
model code at all (SURVEY.md §2.4 — absence table, "Expert parallel: No");
this is net-new capability mandated by the TPU build plan: the `expert` mesh
axis reserved in parallel/mesh.py becomes live here.

Design (vs a torch transliteration that loops over experts):
- **Static-shape capacity routing**: every token picks top-k experts; tokens
  are scattered into a fixed (n_experts, capacity, embed) buffer (overflow
  drops, standard GShard/Switch semantics), experts run as ONE batched einsum
  on the MXU, and results gather back with routing weights. No data-dependent
  shapes, no per-expert Python loops — XLA sees three dense einsums.
- **Expert parallelism**: the buffer's leading axis carries the logical
  "expert" axis → sharded over the `expert` mesh axis. Training leaves the
  sharding to GSPMD (the scatter/gather around the constrained buffer
  becomes the all-to-all); INFERENCE with an expert axis runs the expert
  FFN under an explicit ``shard_map`` (_expert_ffn_sharded) so the int4
  Pallas unpack kernel — an opaque custom call the SPMD partitioner cannot
  shard — partitions too. Expert weights never move: each shard holds
  X/ep experts, composable with tensor parallelism on the mlp axis
  (EP4 x TP2 on a 2x4 mesh).
- **f32 router** with optional z-loss, load-balance aux loss (Switch-style,
  generalized to top-k the way Mixtral's is), top-k weight renormalization.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]


def moe_capacity(n_tokens: int, n_experts: int, k: int,
                 capacity_factor: float) -> int:
    """Per-expert token capacity: ceil(k·G/X · factor), floor 4."""
    return max(4, int(math.ceil(k * n_tokens / n_experts * capacity_factor)))


def route_top_k(router_logits: jax.Array, k: int, norm_topk: bool = True
                ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(G, X) f32 logits -> (weights (G,k), expert ids (G,k), probs (G,X)).

    Softmax over ALL experts, then top-k. ``norm_topk=True`` renormalizes
    over the chosen k (Mixtral's convention); False keeps the raw softmax
    probabilities as the combine weights (DeepSeek-V2-Lite:
    norm_topk_prob=false — the selected experts' weights sum to <1)."""
    probs = jax.nn.softmax(router_logits, axis=-1)
    top_p, top_idx = jax.lax.top_k(probs, k)
    if norm_topk:
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    return top_p, top_idx, probs


def route_top_k_v3(router_logits: jax.Array, k: int, *,
                   correction_bias: jax.Array, n_group: int,
                   topk_group: int, norm_topk: bool,
                   routed_scaling: float
                   ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """DeepSeek-V3 routing: SIGMOID scores; selection adds the aux-free
    load-balancing ``e_score_correction_bias`` and is GROUP-LIMITED
    (per-group score = sum of its top-2 biased scores; only the best
    ``topk_group`` groups' experts are eligible); combine weights gather
    the RAW sigmoid scores at the chosen experts, renormalize over the k
    (+1e-20), and scale by ``routed_scaling``. Returns (weights (G,k),
    ids (G,k), raw scores (G,X))."""
    g_tokens, x = router_logits.shape
    scores = jax.nn.sigmoid(router_logits)                  # (G, X)
    biased = scores + correction_bias[None, :]
    per_group = biased.reshape(g_tokens, n_group, x // n_group)
    group_scores = jnp.sum(jax.lax.top_k(per_group, 2)[0], axis=-1)
    _, group_idx = jax.lax.top_k(group_scores, topk_group)  # (G, tg)
    group_mask = jnp.sum(jax.nn.one_hot(group_idx, n_group,
                                        dtype=biased.dtype), axis=1)
    eligible = jnp.repeat(group_mask, x // n_group, axis=-1)
    choice = jnp.where(eligible > 0, biased, 0.0)           # masked_fill 0
    _, top_idx = jax.lax.top_k(choice, k)
    top_w = jnp.take_along_axis(scores, top_idx, axis=1)    # RAW scores
    if norm_topk:
        top_w = top_w / (jnp.sum(top_w, axis=-1, keepdims=True) + 1e-20)
    return top_w * routed_scaling, top_idx, scores


def load_balance_loss(probs: jax.Array, top_idx: jax.Array,
                      n_experts: int, k: int) -> jax.Array:
    """Switch-transformer aux loss generalized to top-k: X · Σ_x f_x · p_x,
    f_x = fraction of (token, slot) assignments routed to expert x (÷k so a
    perfectly uniform router scores 1.0), p_x = mean router probability."""
    onehot = jax.nn.one_hot(top_idx, n_experts, dtype=jnp.float32)  # (G,k,X)
    f = jnp.mean(jnp.sum(onehot, axis=1), axis=0) / k               # (X,)
    p_mean = jnp.mean(probs, axis=0)                                # (X,)
    return n_experts * jnp.sum(f * p_mean)


def router_z_loss(router_logits: jax.Array) -> jax.Array:
    """Mean squared logsumexp of router logits — keeps them from drifting."""
    z = jax.scipy.special.logsumexp(router_logits, axis=-1)
    return jnp.mean(z ** 2)



def _is_int4(w) -> bool:
    return isinstance(w, dict) and "q4" in w


def _expert_w(w, dtype):
    """(weight, scale_or_None) for an expert leaf: raw array, or int8
    {q8 (..., E, in, out), scale (..., E, 1, out)} from models/quant.py —
    the dequant multiply rides the einsum epilogue exactly like llama._mm,
    so expert HBM reads stay int8 (Mixtral's experts are ~96% of its
    params; without this --int8 barely touches an MoE model). Used by the
    dense reference only — the sparse path goes through _expert_matmul,
    which additionally covers int4."""
    if _is_int4(w):
        raise ValueError("the dense MoE reference does not cover int4 "
                         "expert weights; compare against the raw-weight "
                         "reference instead (tests do)")
    if isinstance(w, dict):
        return w["q8"].astype(dtype), w["scale"].astype(dtype)
    return w.astype(dtype), None


def _expert_matmul(x, w, dtype):
    """Per-expert matmul x (X, C, in) @ w (X, in, out) -> (X, C, out) for
    every expert-leaf form:

    - raw array (X, in, out);
    - int8 {q8 (X, in, out), scale (X, 1, out)} — dequant in the einsum
      epilogue, HBM reads stay int8;
    - int4 {q4 (X, in/2, out), scale (X, g, 1, out)} — each expert's
      packed weight goes through the SAME 2D unpack kernel as the dense
      int4 path (ops/int4_matmul.py), batched over the expert axis.
    """
    if _is_int4(w):
        from ..ops.int4_matmul import int4_expert_matmul
        return int4_expert_matmul(x.astype(dtype), w["q4"], w["scale"])
    if isinstance(w, dict):
        return (jnp.einsum("xci,xio->xco", x, w["q8"].astype(dtype))
                * w["scale"].astype(dtype))   # (X, 1, out) broadcasts over C
    return jnp.einsum("xci,xio->xco", x, w.astype(dtype))


def _expert_ffn_sharded(buf, we_gate, we_up, we_down, *, mesh, activation,
                        dtype):
    """Expert-parallel FFN over the dispatch buffer via shard_map.

    The serving path's EP island: each shard of the ``expert`` mesh axis
    holds X/ep experts' weights and runs their gate/up/down matmuls
    locally; the surrounding scatter/combine stays in GSPMD land, so the
    slice-in / all-gather-out ARE the dispatch/combine collectives.
    Composes with tensor parallelism: raw/int8 expert weights shard their
    mlp axis over ``tensor`` (down contraction psums, megatron-style);
    int4 packed weights replicate over ``tensor`` (their contraction axis
    is 2x-packed and 128-grouped so it cannot shard, and out-sharding
    would force an all-gather before the combine) — per-chip expert bytes
    still drop by the EP factor, which is the memory lever int4 EP is
    for. shard_map rather than GSPMD because the int4 Pallas kernel is an
    opaque custom call the SPMD partitioner cannot shard (the same reason
    ops/int4_matmul.int4_matmul_sharded exists for the dense path)."""
    from jax.sharding import PartitionSpec as P

    from ..ops.ring_attention import shard_map_compat
    from ..parallel.mesh import AXES

    x_experts = buf.shape[0]
    ep = mesh.shape.get(AXES.EXPERT, 1)
    tp = mesh.shape.get(AXES.TENSOR, 1)
    if x_experts % ep:
        raise ValueError(f"expert mesh axis {ep} must divide n_experts "
                         f"{x_experts}")
    int4 = _is_int4(we_gate)
    # mention tensor in the specs only when it is a real axis: at tp=1 a
    # tensor-annotated input would type the output as non-replicated over
    # tensor with no psum to restore it, tripping shard_map's rep check
    tens = AXES.TENSOR if tp > 1 else None

    def w_spec(w, *, down: bool):
        if _is_int4(w):
            return {"q4": P(AXES.EXPERT, None, None),
                    "scale": P(AXES.EXPERT, None, None, None)}
        if isinstance(w, dict):  # int8: scale (X, 1, out) follows the out axis
            if down:
                return {"q8": P(AXES.EXPERT, tens, None),
                        "scale": P(AXES.EXPERT, None, None)}
            return {"q8": P(AXES.EXPERT, None, tens),
                    "scale": P(AXES.EXPERT, None, tens)}
        return (P(AXES.EXPERT, tens, None) if down
                else P(AXES.EXPERT, None, tens))

    def ffn(buf_l, wg, wu, wd):
        gate = _expert_matmul(buf_l, wg, dtype)
        up = _expert_matmul(buf_l, wu, dtype)
        out = _expert_matmul(activation(gate) * up, wd, dtype)
        if tp > 1 and not int4:
            # raw/int8 shard the mlp axis over tensor, so the down matmul
            # holds a partial sum over the contraction — reduce it; int4
            # replicates over tensor and needs none
            out = jax.lax.psum(out, AXES.TENSOR)
        return out

    fn = shard_map_compat(
        ffn, mesh,
        in_specs=(P(AXES.EXPERT, None, None),
                  w_spec(we_gate, down=False), w_spec(we_up, down=False),
                  w_spec(we_down, down=True)),
        out_specs=P(AXES.EXPERT, None, None),
        # int4's pallas_call has no replication rule for the axes its
        # replicated operands don't mention (shard_map_compat docstring);
        # the raw/int8 einsum body type-checks, so keep the check there
        check=not int4)
    return fn(buf, we_gate, we_up, we_down)


def moe_mlp(h: jax.Array, router_w: jax.Array, we_gate,
            we_up, we_down, *, n_experts_per_tok: int,
            capacity_factor: float, activation, dtype, constrain=None,
            norm_topk: bool = True, router_bias=None,
            router_n_group: int = 0, router_topk_group: int = 0,
            routed_scaling: float = 1.0, mesh=None
            ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Sparse MoE MLP on normed activations.

    h (B,S,E); router_w (E,X); we_* (X,E,M)/(X,M,E) raw arrays, or int8
    {q8, scale} / int4 {q4, scale} dict leaves from models/quant.py
    (see _expert_matmul).
    Returns (out (B,S,E), load_balance_aux, router_z) — aux terms are
    UNSCALED; the caller applies its coefficients (so inference paths can
    just drop them).
    ``constrain(x, logical_axes)`` optionally applies sharding constraints.
    ``mesh``: when it carries an ``expert`` axis (or the expert leaves are
    int4, which GSPMD cannot partition), the expert FFN runs under an
    explicit shard_map (_expert_ffn_sharded) — the serving EP path.
    Training passes mesh=None and keeps the GSPMD/constraint path (the
    shard_map island has no int4 VJP and training never needs one).
    """
    b, s, e = h.shape
    x_experts = router_w.shape[-1]
    k = n_experts_per_tok
    g = b * s
    cap = moe_capacity(g, x_experts, k, capacity_factor)
    cons = constrain or (lambda t, axes: t)

    ht = h.reshape(g, e)
    router_logits = ht.astype(jnp.float32) @ router_w.astype(jnp.float32)
    if router_bias is not None:
        # DeepSeek-V3 sigmoid routing (aux-free balancing via the bias;
        # the load-balance aux below is ZERO for this mode — V3 adjusts
        # the bias outside the gradient instead of an aux loss)
        top_p, top_idx, probs = route_top_k_v3(
            router_logits, k, correction_bias=router_bias.astype(jnp.float32),
            n_group=router_n_group, topk_group=router_topk_group,
            norm_topk=norm_topk, routed_scaling=routed_scaling)
    else:
        top_p, top_idx, probs = route_top_k(router_logits, k, norm_topk)

    # position of each (token, slot) assignment within its expert's buffer:
    # exclusive running count of earlier assignments to the same expert
    onehot = jax.nn.one_hot(top_idx, x_experts, dtype=jnp.int32)    # (G,k,X)
    flat = onehot.reshape(g * k, x_experts)
    pos_in_expert = jnp.sum((jnp.cumsum(flat, axis=0) - flat) * flat, axis=-1)
    eid = top_idx.reshape(g * k)
    keep = pos_in_expert < cap
    # overflow assignments scatter out of bounds, which mode="drop" discards
    slot = jnp.where(keep, eid * cap + pos_in_expert, x_experts * cap)

    # dispatch: (G·k, E) token copies scattered into the expert buffer.
    # With the buffer sharded over the expert mesh axis and tokens over the
    # batch axes, this scatter IS the all-to-all.
    tok_rep = jnp.broadcast_to(ht[:, None], (g, k, e)).reshape(g * k, e)
    buf = jnp.zeros((x_experts * cap, e), h.dtype)
    buf = buf.at[slot].set(tok_rep.astype(h.dtype), mode="drop")
    buf = buf.reshape(x_experts, cap, e)
    buf = cons(buf, ("expert", None, None))

    # all experts in one batched einsum each — MXU-shaped, weights stationary
    from ..parallel.mesh import AXES
    use_ep = mesh is not None and (mesh.shape.get(AXES.EXPERT, 1) > 1
                                   or _is_int4(we_gate))
    if use_ep:
        out = _expert_ffn_sharded(buf, we_gate, we_up, we_down, mesh=mesh,
                                  activation=activation, dtype=dtype)
    else:
        gate = _expert_matmul(buf, we_gate, dtype)
        up = _expert_matmul(buf, we_up, dtype)
        act = cons(activation(gate) * up, ("expert", None, "act_mlp"))
        out = _expert_matmul(act, we_down, dtype)
    out_flat = out.reshape(x_experts * cap, e)

    # combine: gather each assignment's result, zero the dropped ones,
    # weighted-sum the k slots per token
    gathered = jnp.take(out_flat, jnp.minimum(slot, x_experts * cap - 1), axis=0)
    gathered = jnp.where(keep[:, None], gathered, jnp.zeros_like(gathered))
    y = jnp.sum(gathered.reshape(g, k, e)
                * top_p.reshape(g, k, 1).astype(h.dtype), axis=1)
    y = y.reshape(b, s, e)

    if router_bias is not None:
        # V3: aux-FREE balancing (the bias is adjusted outside the
        # gradient) — both the load-balance aux AND the z-loss are zero;
        # a softmax-style logsumexp pull on sigmoid logits would shift
        # the score/bias balance the recipe depends on
        aux = jnp.float32(0.0)
        z = jnp.float32(0.0)
    else:
        aux = load_balance_loss(probs, top_idx, x_experts, k)
        z = router_z_loss(router_logits)
    return y, aux, z


def moe_mlp_dense_reference(h: jax.Array, router_w: jax.Array,
                            we_gate, we_up,
                            we_down, *, n_experts_per_tok: int,
                            activation, dtype,
                            norm_topk: bool = True) -> jax.Array:
    """Dense reference: run EVERY expert on every token, combine with the
    top-k weights (zero elsewhere; ``norm_topk`` as in route_top_k — the
    reference must follow the SAME routing convention as the sparse path
    it grounds). X× the FLOPs of the sparse path but no capacity drops —
    used by tests as ground truth."""
    b, s, e = h.shape
    x_experts = router_w.shape[-1]
    ht = h.reshape(b * s, e)
    logits = ht.astype(jnp.float32) @ router_w.astype(jnp.float32)
    top_p, top_idx, _ = route_top_k(logits, n_experts_per_tok, norm_topk)
    weights = jnp.zeros((b * s, x_experts), jnp.float32)
    weights = jax.vmap(lambda w, p, i: w.at[i].set(p))(weights, top_p, top_idx)
    wg, sg = _expert_w(we_gate, dtype)
    wu, su = _expert_w(we_up, dtype)
    wd, sd = _expert_w(we_down, dtype)
    gate = jnp.einsum("ge,xem->gxm", ht, wg)
    up = jnp.einsum("ge,xem->gxm", ht, wu)
    if sg is not None:
        # scale (x, 1, m) -> (x, m): right-aligns against (g, x, m)
        gate = gate * sg[..., 0, :]
        up = up * su[..., 0, :]
    out = jnp.einsum("gxm,xme->gxe", activation(gate) * up, wd)
    if sd is not None:
        out = out * sd[..., 0, :]
    y = jnp.einsum("gxe,gx->ge", out.astype(jnp.float32), weights)
    return y.reshape(b, s, e).astype(h.dtype)
