"""LoRA fine-tuning: rank-decomposed adapters on frozen base weights.

MaxText-style parameter-efficient fine-tuning for the same decoder. A LoRA-
wrapped projection is a dict leaf ``{"w": base, "lora_a": (..., in, r),
"lora_b": (..., r, out), "scale": alpha/r}``; the model's matmul helper
(llama._mm) computes

    y = x @ stop_gradient(w) + ((x @ A) @ B) * scale

so gradients exist ONLY for A/B — XLA dead-code-eliminates the base weight's
backward matmuls, which is what makes LoRA cheap. ``lora_mask`` feeds both
the label-partitioned optimizer (zero updates, no Adam moments for frozen
leaves) and the train step's stop_gradient pass (no gradient HBM for any
frozen leaf, adapter-only grad_norm) — that, not the forward, is where
LoRA's memory win lives.

A ~ N(0, 1/d_in) (Kaiming-style fan-in), B = 0 (standard LoRA): step 0 is
exactly the base model.
``merge_lora`` folds ``w + A @ B * scale`` back into plain leaves for
serving/export (including to_hf_state_dict). Adapters are tiny, so they stay
replicated on every mesh device — no sharding rules needed.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from .llama import LlamaConfig, Params

__all__ = ["LoraConfig", "apply_lora", "merge_lora", "lora_mask",
           "is_lora", "lora_param_count", "extract_adapter", "save_adapter",
           "load_adapter"]

_DEFAULT_TARGETS = ("wq", "wv")  # the original-paper default


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    alpha: float = 16.0
    # which stacked-layer projections get adapters; any of
    # wq/wk/wv/wo/w_gate/w_up/w_down
    targets: tuple[str, ...] = _DEFAULT_TARGETS

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def is_lora(w: Any) -> bool:
    return isinstance(w, dict) and "lora_a" in w


def apply_lora(cfg: LlamaConfig, params: Params, lc: LoraConfig,
               key: jax.Array, mesh=None) -> Params:
    """Wrap the target projections of ``params`` with fresh adapters.
    A is fan-in-scaled gaussian, B = 0, so the wrapped model initially
    computes exactly the base model. ``mesh`` replicates adapters across it."""
    from jax.sharding import NamedSharding, PartitionSpec

    if not lc.targets:
        raise ValueError("LoRA with no targets would freeze the whole model "
                         "and train nothing")
    unknown = set(lc.targets) - {"wq", "wk", "wv", "wo",
                                 "w_gate", "w_up", "w_down"}
    if unknown:
        raise ValueError(f"unknown LoRA targets {sorted(unknown)}")
    replicate = (NamedSharding(mesh, PartitionSpec()) if mesh is not None
                 else None)
    keys = jax.random.split(key, max(len(lc.targets), 1))
    layers = dict(params["layers"])
    for k, name in zip(keys, lc.targets):
        if name not in layers:
            raise ValueError(f"LoRA target {name!r} not in this model "
                             f"(MoE configs have no dense mlp weights)")
        w = layers[name]
        if is_lora(w):
            raise ValueError(f"{name} already has a LoRA adapter")
        d_in, d_out = w.shape[-2], w.shape[-1]
        lead = w.shape[:-2]
        a = (jax.random.normal(k, (*lead, d_in, lc.rank), jnp.float32)
             / jnp.sqrt(d_in)).astype(w.dtype)  # Kaiming-style fan-in init
        b = jnp.zeros((*lead, lc.rank, d_out), w.dtype)
        # scale is shaped (n_layers,) so the layers tree stays lax.scan-able
        # (every leaf needs the leading layer axis; scan hands each layer a
        # () scalar that broadcasts in the matmul helper)
        scale = jnp.full(lead or (), lc.scale, jnp.float32)
        if replicate is not None:
            a = jax.device_put(a, replicate)
            b = jax.device_put(b, replicate)
            scale = jax.device_put(scale, replicate)
        layers[name] = {"w": w, "lora_a": a, "lora_b": b, "scale": scale}
    out = dict(params)
    out["layers"] = layers
    return out


def merge_lora(params: Params) -> Params:
    """Fold every adapter into its base weight: plain tree back (serving,
    export, or continued full fine-tuning)."""
    def fold(w):
        if is_lora(w):
            # delta math in f32 (adapters are tiny), but NEVER upcast the
            # stacked base weight — an f32 copy of a 70B-scale leaf is a
            # multi-GB transient (same hazard quant.py avoids)
            delta = jnp.einsum("...ir,...ro->...io",
                               w["lora_a"].astype(jnp.float32),
                               w["lora_b"].astype(jnp.float32))
            delta = delta * jnp.reshape(w["scale"],
                                        w["scale"].shape + (1, 1))
            return w["w"] + delta.astype(w["w"].dtype)
        return w
    layers = {k: fold(v) for k, v in params["layers"].items()}
    out = dict(params)
    out["layers"] = layers
    return out


def lora_mask(params: Params) -> Params:
    """Boolean tree (same structure): True only on adapter leaves — feeds
    the label-partitioned optimizer (train.make_optimizer) so the frozen base
    gets zero updates and no optimizer state, and the train step's
    stop_gradient pass so no frozen-leaf gradients are even computed."""
    def mask(w):
        if is_lora(w):
            return {"w": False, "lora_a": True, "lora_b": True, "scale": False}
        return False

    def walk(node):
        if isinstance(node, dict) and not is_lora(node):
            return {k: walk(v) for k, v in node.items()}
        return mask(node) if is_lora(node) else False

    return walk(params)


def lora_param_count(params: Params) -> int:
    n = 0
    for w in params["layers"].values():
        if is_lora(w):
            n += w["lora_a"].size + w["lora_b"].size
    return n


def extract_adapter(params: Params) -> dict:
    """LoRA-wrapped params -> {target: {"a": (L, in, r), "b": (L, r, out),
    "scale": (L,)}} — the shape the serving engine's register_adapter and
    the adapter file format share."""
    out = {}
    for name, w in params["layers"].items():
        if is_lora(w):
            out[name] = {"a": w["lora_a"], "b": w["lora_b"],
                         "scale": w["scale"]}
    if not out:
        raise ValueError("params carry no LoRA adapters")
    return out


def save_adapter(path: str, params_or_adapter) -> str:
    """Write an adapter to a portable .npz ("wq.a", "wq.b", "wq.scale", ...)
    — the train -> serve hand-off artifact (a full orbax checkpoint carries
    the frozen base too; the adapter alone is a few MB). Returns the path
    actually written: np.savez appends ".npz" itself, so we normalize first
    rather than report a filename that doesn't exist."""
    import numpy as np
    if not path.endswith(".npz"):
        path += ".npz"
    src = (extract_adapter(params_or_adapter)
           if "layers" in params_or_adapter else params_or_adapter)
    flat = {}
    for t, ad in src.items():
        for k in ("a", "b", "scale"):
            flat[f"{t}.{k}"] = np.asarray(ad[k])
    np.savez(path, **flat)
    return path


def load_adapter(path: str) -> dict:
    """Read a save_adapter() .npz back into {target: {"a","b","scale"}}."""
    import numpy as np
    with np.load(path) as z:
        out: dict = {}
        for key in z.files:
            t, _, k = key.rpartition(".")
            if not t or k not in ("a", "b", "scale"):
                raise ValueError(f"{path}: unexpected entry {key!r}")
            out.setdefault(t, {})[k] = z[key]
    for t, ad in out.items():
        missing = {"a", "b", "scale"} - set(ad)
        if missing:
            raise ValueError(f"{path}: {t} missing {sorted(missing)}")
    return out
