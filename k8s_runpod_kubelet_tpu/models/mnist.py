"""Flax CNN for the single-chip MNIST smoke workload (BASELINE.json config 2)."""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MnistConfig:
    features: tuple = (32, 64)
    dense: int = 256
    classes: int = 10


def mnist_config() -> MnistConfig:
    return MnistConfig()


class MnistCNN(nn.Module):
    cfg: MnistConfig = MnistConfig()

    @nn.compact
    def __call__(self, x):  # x: (B, 28, 28, 1)
        for f in self.cfg.features:
            x = nn.Conv(f, (3, 3))(x)
            x = nn.relu(x)
            x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.cfg.dense)(x))
        return nn.Dense(self.cfg.classes)(x)
